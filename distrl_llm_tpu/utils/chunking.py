"""Batch-chunking math for dispatching prompts across rollout workers.

Behavioral parity with the reference's Trainer statics
(distributed_trainer.py:77–169): ``chunk_sizes`` returns per-worker batch
sizes — actors first, then learners at a fixed ``learner_chunk_size`` — with
the same degradation policy when the batch is smaller than the worker pool
(actors are prioritized, learners shrink or drop; SURVEY §4 "unit" targets).
``split_dict_lists`` slices a dict-of-lists into those chunks.
"""

from __future__ import annotations

import logging
from typing import Any, Mapping, Sequence

log = logging.getLogger(__name__)


def chunk_sizes(
    batch_size: int,
    num_actors: int,
    num_learners: int = 1,
    learner_chunk_size: int = 1,
) -> list[int]:
    """Per-worker chunk sizes: ``num_actors`` near-even actor chunks followed by
    ``num_learners`` chunks of ``learner_chunk_size``.

    Under-provisioned batches (batch < actors + learners·chunk) follow the
    reference's policy (distributed_trainer.py:98–124): give every actor at
    least one item if possible, then fit learners into the remainder with a
    shrunken chunk size; if even the actors don't fit, the batch is spread over
    the first ``batch_size`` actors and learners get nothing.
    """
    if batch_size <= 0 or num_learners <= 0 or num_actors < 0:
        raise ValueError("Batch size, number of learners and number of actors must be positive")

    learner_total = learner_chunk_size * num_learners

    if batch_size < num_actors + learner_total:
        log.warning(
            "batch size (%d) is smaller than actors + learners need (%d)",
            batch_size,
            num_actors + learner_total,
        )
        if batch_size >= num_actors:
            remaining = batch_size - num_actors
            if remaining > 0 and num_learners > 0:
                learner_chunk_size = max(1, remaining // num_learners)
                num_learners = min(num_learners, remaining // learner_chunk_size)
                learner_total = learner_chunk_size * num_learners
            else:
                num_learners, learner_total = 0, 0
        else:
            num_actors = batch_size
            num_learners, learner_total = 0, 0

    actor_total = batch_size - learner_total
    sizes: list[int] = []
    if num_actors > 0:
        base, extra = divmod(actor_total, num_actors)
        sizes = [base + (1 if i < extra else 0) for i in range(num_actors)]
    sizes.extend([learner_chunk_size] * num_learners)
    return sizes


def split_dict_lists(
    data: Mapping[str, Sequence[Any]], sizes: Sequence[int] | int
) -> list[dict[str, list[Any]]]:
    """Slice every list in ``data`` into consecutive chunks of ``sizes``
    (distributed_trainer.py:142–169). All lists must share a length equal to
    ``sum(sizes)``."""
    if isinstance(sizes, int):
        sizes = [sizes]

    length = len(next(iter(data.values())))
    if any(len(v) != length for v in data.values()):
        raise ValueError("All lists in the dictionary must have the same length")
    if sum(sizes) != length:
        raise ValueError(
            f"Sum of chunk sizes ({sum(sizes)}) must equal the length of lists ({length})"
        )

    chunks = []
    start = 0
    for size in sizes:
        chunks.append({k: list(v[start : start + size]) for k, v in data.items()})
        start += size
    return chunks


def merge_candidates(
    candidates: Sequence[Mapping[str, Any]],
) -> tuple[list[Any], list[Any], list[Any]]:
    """Flatten per-worker candidate dicts into parallel (problems, answers,
    rewards) lists (distributed_trainer.py:221–230)."""
    problems: list[Any] = []
    answers: list[Any] = []
    rewards: list[Any] = []
    for cand in candidates:
        for a, p, r in zip(cand["answers"], cand["problem"], cand["rewards"]):
            problems.extend(p)
            answers.extend(a)
            rewards.extend(r)
    return problems, answers, rewards


def even_chunks(total: int, parts: int) -> list[int]:
    """Split ``total`` items into ``parts`` near-even chunk sizes, remainder
    spread over the leading chunks (distributed_trainer.py:312–314)."""
    base, extra = divmod(total, parts)
    return [base + (1 if i < extra else 0) for i in range(parts)]
