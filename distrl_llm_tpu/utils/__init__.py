from distrl_llm_tpu.utils.chunking import (  # noqa: F401
    chunk_sizes,
    even_chunks,
    merge_candidates,
    split_dict_lists,
)
