from distrl_llm_tpu.parallel.mesh import AXES, RoleMeshes, build_role_meshes  # noqa: F401
from distrl_llm_tpu.parallel.partition import (  # noqa: F401
    batch_spec,
    param_specs,
    replicated,
    shard_tree,
)
