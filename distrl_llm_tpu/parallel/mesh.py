"""Device-mesh topology: global mesh, parallelism axes, and role submeshes.

The reference maps roles (rollout actors / learners) to whole GPUs via Ray
placement groups (distributed_actor.py:517–585). Here roles are partitions of
the device set: the rollout submesh and learner submesh each get their own
``jax.sharding.Mesh`` with axes

    ("dp", "fsdp", "sp", "tp")

- dp:   data parallel — batch sharding, gradient psum (the N6 equivalent)
- fsdp: parameter sharding of learner state (ZeRO-style)
- sp:   sequence parallel — ring attention over long context
- tp:   tensor parallel — heads/MLP sharding within a model replica

With fewer devices than roles (e.g. the 1-chip dev box) the roles time-share
one mesh, matching the reference's hybrid learner-generation in spirit
(README.md:19).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

from distrl_llm_tpu.config import MeshConfig

AXES = ("dp", "fsdp", "sp", "tp")


def _make_mesh(devices: list, tp: int, sp: int, fsdp: int) -> Mesh:
    n = len(devices)
    denom = tp * sp * fsdp
    if n % denom != 0:
        raise ValueError(f"{n} devices not divisible by tp*sp*fsdp={denom}")
    dp = n // denom
    arr = np.asarray(devices).reshape(dp, fsdp, sp, tp)
    return Mesh(arr, AXES)


@dataclass
class RoleMeshes:
    """The carved-up device set. ``rollout`` serves generation; ``learner``
    serves the train step. ``timeshared`` means both are the same mesh."""

    rollout: Mesh
    learner: Mesh
    timeshared: bool

    @property
    def rollout_dp(self) -> int:
        return self.rollout.shape["dp"]

    @property
    def learner_dp(self) -> int:
        return self.learner.shape["dp"]


def build_role_meshes(cfg: MeshConfig, devices: list | None = None) -> RoleMeshes:
    """Carve devices into rollout/learner submeshes per the configured role
    counts. Each role is one dp-group of ``tp·sp·fsdp`` chips: actors first,
    learners after, mirroring the reference's first-N/next-M GPU assignment
    (distributed_actor.py:535–537)."""
    if devices is None:
        devices = jax.devices()
    per_role = cfg.tp * cfg.sp * cfg.fsdp
    needed = cfg.num_roles * per_role
    if len(devices) < needed:
        if not cfg.allow_timeshare:
            raise RuntimeError(
                f"Not enough devices. Available: {len(devices)}, Required: {needed}"
            )
        usable = max(per_role, len(devices) - len(devices) % per_role)
        if len(devices) < per_role:
            raise RuntimeError(
                f"Need at least tp*sp*fsdp={per_role} devices, have {len(devices)}"
            )
        mesh = _make_mesh(devices[:usable], cfg.tp, cfg.sp, cfg.fsdp)
        return RoleMeshes(rollout=mesh, learner=mesh, timeshared=True)

    if cfg.number_of_actors == 0:
        # learners generate too (reference allows actors=0,
        # train_distributed.py:27) — rollout aliases the learner mesh
        learner = _make_mesh(
            devices[: cfg.number_of_learners * per_role], cfg.tp, cfg.sp, cfg.fsdp
        )
        return RoleMeshes(rollout=learner, learner=learner, timeshared=True)

    n_rollout = cfg.number_of_actors * per_role
    rollout = _make_mesh(devices[:n_rollout], cfg.tp, cfg.sp, cfg.fsdp)
    learner = _make_mesh(
        devices[n_rollout : n_rollout + cfg.number_of_learners * per_role],
        cfg.tp, cfg.sp, cfg.fsdp,
    )
    return RoleMeshes(rollout=rollout, learner=learner, timeshared=False)
