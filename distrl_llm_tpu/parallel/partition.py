"""PartitionSpec rules for the decoder param/LoRA/cache pytrees.

GSPMD does the heavy lifting: we annotate parameters and batch inputs, XLA
inserts the collectives (SURVEY §2c — TP sharding replaces the reference's
unused vLLM TP; fsdp shards learner state; dp shards the batch). Specs are
assigned by param-tree path so they survive structural additions like
quantized weight containers.

Layout conventions (models/transformer.py):
  layers/w*:   [L, in, out]  → out over "tp" for up-projections (qkv, gate,
               up), in over "tp" for down-projections (o, down) — Megatron
               style, so the pair needs no resharding between them.
  embed:       [V, D] vocab over "tp" (logits psum'd by GSPMD), D over "fsdp".
  lm_head:     [D, V] V over "tp".
  lora a/b:    factor dims follow the base weight's sharded dim; the rank dim
               is always replicated.
  kv cache:    per-layer tuples of [B, K, hd, S]; batch over "dp", kv heads
               over "tp" (build it with models.init_kv_cache).
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = dict[str, Any]

# layer weights whose OUT dim is tp-sharded (column parallel)
_COL = {"wq", "wk", "wv", "w_gate", "w_up"}
# layer weights whose IN dim is tp-sharded (row parallel)
_ROW = {"wo", "w_down"}


def _spec_for_path(path: tuple[str, ...], shape: tuple[int, ...]) -> P:
    ndim = len(shape)
    name = path[-1]
    if name in ("a", "b"):  # LoRA factor: path is (..., "layers", target, "a"|"b")
        target = path[-2]
        if name == "a":  # [L, in, r]
            return P(None, "tp" if target in _ROW else "fsdp", None)
        return P(None, None, "tp" if target in _COL else "fsdp")  # [L, r, out]
    if name == "embed":
        return P("tp", "fsdp")
    if name == "lm_head":
        return P("fsdp", "tp")
    if name in ("final_norm", "attn_norm", "mlp_norm"):
        return P(*([None] * ndim))
    if name in _COL:
        return P(None, "fsdp", "tp")
    if name in _ROW:
        return P(None, "tp", "fsdp")
    if name.startswith("b"):  # projection biases [L, out]
        return P(None, "tp") if name in ("bq", "bk", "bv") else P(None, "fsdp")
    if name in ("k", "v"):  # kv cache: per-layer [B, K, hd, S] (S minormost)
        return P("dp", "tp", None, None)
    if name in ("q", "scale") and len(path) >= 2 and path[-2] in (_COL | _ROW):
        # quantized weight container (ops/quant.py): q [L, G, g, out],
        # scale [L, G, 1, out]. The base weight's input-dim sharding goes on
        # G when there are multiple groups (blockwise int4 — contiguous groups
        # per shard, so the dequant reshape [G, g] → [G·g] stays local); with
        # a single group (per-column int8, G=1) it goes on g for q and is
        # dropped for scale (whose g dim is 1).
        target = path[-2]
        in_ax, out_ax = ("fsdp", "tp") if target in _COL else ("tp", "fsdp")
        if shape[1] > 1:  # [L, G>1, ...]: shard the group axis
            return P(None, in_ax, None, out_ax)
        if name == "q":
            return P(None, None, in_ax, out_ax)
        return P(None, None, None, out_ax)  # scale [L, 1, 1, out]
    return P(*([None] * ndim))


def _tree_specs(tree: Params) -> Params:
    def walk(path: tuple[str, ...], node):
        if isinstance(node, dict):
            return {k: walk(path + (k,), v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):  # per-layer cache tuples
            return type(node)(walk(path, v) for v in node)
        if node is None:
            return None
        return _spec_for_path(path, tuple(getattr(node, "shape", ())))

    return walk((), tree)


def param_specs(params: Params) -> Params:
    """PartitionSpec tree matching ``params``' structure (base, LoRA, or cache)."""
    return _tree_specs(params)


def shard_tree(tree: Params, mesh: Mesh, specs: Params | None = None) -> Params:
    """device_put the tree onto ``mesh`` with its specs (host→device scatter)."""
    if specs is None:
        specs = param_specs(tree)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def opt_state_specs(opt_state: Any) -> Any:
    """Specs for an optimizer-state tree. Optax moment trees mirror the param
    tree's dict structure (mu/nu hold the same nested dicts), so each state
    leaf's DictKey path suffix IS a param path — route it through the same
    ``_spec_for_path`` rules. Leaves whose shape no longer matches the rule
    (step counts, blockwise-quantized flat payloads) are replicated.

    Needed because ``jit(optimizer.init)`` does NOT propagate input shardings:
    init only uses input *shapes*, so the compiled program has no array inputs
    and its outputs land on the default device."""
    from jax.tree_util import DictKey

    def spec_for(path, leaf):
        ndim = len(getattr(leaf, "shape", ()))
        names = tuple(k.key for k in path if isinstance(k, DictKey))
        if names:
            s = _spec_for_path(names, tuple(leaf.shape))
            if len(s) == ndim:
                return s
        return P(*([None] * ndim))

    return jax.tree_util.tree_map_with_path(spec_for, opt_state)


def shard_opt_state(opt_state: Any, mesh: Mesh) -> Any:
    """Place optimizer state on ``mesh``, moments sharded like their params
    (explicit FSDP sharding of learner state — SURVEY §2c)."""
    specs = opt_state_specs(opt_state)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), opt_state, specs
    )


def batch_spec() -> P:
    """Activations/batch inputs: leading dim over dp."""
    return P("dp")


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
