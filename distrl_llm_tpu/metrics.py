"""Metrics sinks: the reference's wandb contract behind a pluggable interface.

The reference hardcodes wandb (distributed_trainer.py:237–239, :348–366,
:412–415). We keep the exact metric names and step semantics — parity lets
reward curves overlay against the reference's published runs (media/*.png) —
but make the sink pluggable: wandb when importable/configured, a JSONL file
sink for offline TPU hosts, a null sink for tests.

Metric-name contract (SURVEY §5 "metrics"):
  train (per batch step, distributed_trainer.py:348–366):
    loss, mean_accuracy_reward, min_accuracy_reward, max_accuracy_reward,
    mean_format_reward, mean_token_length, episode, total_batch_steps,
    total_samples_processed, timing/update_duration, timing/reward_duration,
    timing/generation_duration
  eval (distributed_trainer.py:412–415):
    eval/pass@1(mean{n}), eval/BoN({n}), eval/mean_token_length,
    timing/eval_duration
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Mapping, Protocol


class MetricsSink(Protocol):
    def log(self, metrics: Mapping[str, Any], step: int) -> None: ...
    def finish(self) -> None: ...


class NullSink:
    """Discard everything (tests, dry runs)."""

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        pass

    def finish(self) -> None:
        pass


class MemorySink:
    """Keep everything in a list (assertions in tests)."""

    def __init__(self):
        self.records: list[tuple[int, dict[str, Any]]] = []

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        self.records.append((step, dict(metrics)))

    def finish(self) -> None:
        pass


class JsonlSink:
    """Append one JSON object per log call — the offline-host default."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        rec = {"_step": step, "_ts": time.time()}
        rec.update({k: _jsonable(v) for k, v in metrics.items()})
        self._f.write(json.dumps(rec) + "\n")

    def finish(self) -> None:
        self._f.close()


class WandbSink:
    """The reference sink: wandb.init(name, config, project) →
    run.log(metrics, step) → finish (distributed_trainer.py:237–239)."""

    def __init__(self, run_name: str | None, project: str, config: Mapping[str, Any]):
        import wandb

        self._run = wandb.init(name=run_name, config=dict(config), project=project)

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        self._run.log(dict(metrics), step=step)

    def finish(self) -> None:
        self._run.finish()


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return float(v) if hasattr(v, "__float__") else str(v)


def make_sink(
    backend: str,
    *,
    run_name: str | None,
    project: str,
    config: Mapping[str, Any],
    run_dir: str = ".",
) -> MetricsSink:
    """``auto`` → wandb if importable and logged in, else jsonl."""
    if backend == "null":
        return NullSink()
    if backend == "jsonl":
        return JsonlSink(os.path.join(run_dir, "metrics.jsonl"))
    if backend in ("wandb", "auto"):
        try:
            return WandbSink(run_name, project, config)
        except Exception:
            if backend == "wandb":
                raise
            return JsonlSink(os.path.join(run_dir, "metrics.jsonl"))
    raise ValueError(f"unknown metrics backend {backend!r}")


class TraceProfiler:
    """jax.profiler trace capture around a configurable step window.

    The reference has wall-clock phase timers only (SURVEY §5 tracing); this
    adds real device traces: call ``step_begin(step)`` before each train step
    and ``finish()`` at shutdown. Traces land in ``profile_dir`` in
    TensorBoard format (``tensorboard --logdir <profile_dir>``).

    ``stop``/``finish`` are idempotent and captures never overlap
    (ISSUE 8): a sentinel-triggered ``request_capture`` window while the
    configured step window is active (or vice versa) is a counted no-op —
    ``jax.profiler.start_trace`` raises on a second concurrent trace, and a
    mid-run incident must never take the training process down with it."""

    def __init__(self, profile_dir: str, start_step: int = 2, num_steps: int = 3):
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False
        self._stop_at = self.stop_step
        self._pending = 0  # requested (sentinel) capture length, in steps
        self.captures_skipped = 0

    def request_capture(self, num_steps: int = 2) -> bool:
        """Ask for a capture window starting at the next ``step_begin``
        (the sentinel's hook). Refused — returning False and counting —
        when a capture is already active or pending, so triggered windows
        cannot collide with the configured step window."""
        if self._active or self._pending:
            self.captures_skipped += 1
            return False
        self._pending = max(int(num_steps), 1)
        return True

    def _start(self) -> bool:
        import jax

        try:
            os.makedirs(self.profile_dir, exist_ok=True)
            jax.profiler.start_trace(self.profile_dir)
        except Exception:  # noqa: BLE001 — e.g. a trace some other owner
            # (an outer harness) already has running: skip, don't crash
            self.captures_skipped += 1
            return False
        self._active = True
        return True

    def step_begin(self, step: int) -> None:
        if self._active and step >= self._stop_at:
            self.stop()
        if self._active:
            return
        if self._pending:
            if self._start():
                self._stop_at = step + self._pending
            self._pending = 0
        elif self.start_step <= step < self.stop_step:
            if self._start():
                self._stop_at = self.stop_step

    def stop(self) -> None:
        """Stop the capture in flight; safe to call repeatedly or with no
        capture active."""
        if not self._active:
            return
        self._active = False
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — already stopped elsewhere
            pass

    def finish(self) -> None:
        self.stop()


# Wall-clock phase timing matching the reference's inline time.time() pairs
# (distributed_trainer.py:180/:202, :206/:217, :303/:343, :385/:411). ONE
# implementation owns the timing/*_duration name contract: telemetry's
# PhaseSpans, which additionally records each phase as a trace span (a no-op
# while tracing is off) — kept under the historical name here.
from distrl_llm_tpu.telemetry import PhaseSpans as PhaseTimer  # noqa: E402,F401
