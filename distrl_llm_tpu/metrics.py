"""Metrics sinks: the reference's wandb contract behind a pluggable interface.

The reference hardcodes wandb (distributed_trainer.py:237–239, :348–366,
:412–415). We keep the exact metric names and step semantics — parity lets
reward curves overlay against the reference's published runs (media/*.png) —
but make the sink pluggable: wandb when importable/configured, a JSONL file
sink for offline TPU hosts, a null sink for tests.

Metric-name contract (SURVEY §5 "metrics"):
  train (per batch step, distributed_trainer.py:348–366):
    loss, mean_accuracy_reward, min_accuracy_reward, max_accuracy_reward,
    mean_format_reward, mean_token_length, episode, total_batch_steps,
    total_samples_processed, timing/update_duration, timing/reward_duration,
    timing/generation_duration
  eval (distributed_trainer.py:412–415):
    eval/pass@1(mean{n}), eval/BoN({n}), eval/mean_token_length,
    timing/eval_duration
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Mapping, Protocol


class MetricsSink(Protocol):
    def log(self, metrics: Mapping[str, Any], step: int) -> None: ...
    def finish(self) -> None: ...


class NullSink:
    """Discard everything (tests, dry runs)."""

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        pass

    def finish(self) -> None:
        pass


class MemorySink:
    """Keep everything in a list (assertions in tests)."""

    def __init__(self):
        self.records: list[tuple[int, dict[str, Any]]] = []

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        self.records.append((step, dict(metrics)))

    def finish(self) -> None:
        pass


class JsonlSink:
    """Append one JSON object per log call — the offline-host default."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        rec = {"_step": step, "_ts": time.time()}
        rec.update({k: _jsonable(v) for k, v in metrics.items()})
        self._f.write(json.dumps(rec) + "\n")

    def finish(self) -> None:
        self._f.close()


class WandbSink:
    """The reference sink: wandb.init(name, config, project) →
    run.log(metrics, step) → finish (distributed_trainer.py:237–239)."""

    def __init__(self, run_name: str | None, project: str, config: Mapping[str, Any]):
        import wandb

        self._run = wandb.init(name=run_name, config=dict(config), project=project)

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        self._run.log(dict(metrics), step=step)

    def finish(self) -> None:
        self._run.finish()


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return float(v) if hasattr(v, "__float__") else str(v)


def make_sink(
    backend: str,
    *,
    run_name: str | None,
    project: str,
    config: Mapping[str, Any],
    run_dir: str = ".",
) -> MetricsSink:
    """``auto`` → wandb if importable and logged in, else jsonl."""
    if backend == "null":
        return NullSink()
    if backend == "jsonl":
        return JsonlSink(os.path.join(run_dir, "metrics.jsonl"))
    if backend in ("wandb", "auto"):
        try:
            return WandbSink(run_name, project, config)
        except Exception:
            if backend == "wandb":
                raise
            return JsonlSink(os.path.join(run_dir, "metrics.jsonl"))
    raise ValueError(f"unknown metrics backend {backend!r}")


class TraceProfiler:
    """jax.profiler trace capture around a configurable step window.

    The reference has wall-clock phase timers only (SURVEY §5 tracing); this
    adds real device traces: call ``step_begin(step)`` before each train step
    and ``finish()`` at shutdown. Traces land in ``profile_dir`` in
    TensorBoard format (``tensorboard --logdir <profile_dir>``)."""

    def __init__(self, profile_dir: str, start_step: int = 2, num_steps: int = 3):
        self.profile_dir = profile_dir
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False

    def step_begin(self, step: int) -> None:
        import jax

        if not self._active and self.start_step <= step < self.stop_step:
            os.makedirs(self.profile_dir, exist_ok=True)
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
        elif self._active and step >= self.stop_step:
            jax.profiler.stop_trace()
            self._active = False

    def finish(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


# Wall-clock phase timing matching the reference's inline time.time() pairs
# (distributed_trainer.py:180/:202, :206/:217, :303/:343, :385/:411). ONE
# implementation owns the timing/*_duration name contract: telemetry's
# PhaseSpans, which additionally records each phase as a trace span (a no-op
# while tracing is off) — kept under the historical name here.
from distrl_llm_tpu.telemetry import PhaseSpans as PhaseTimer  # noqa: E402,F401
