"""Ulysses attention: sequence parallelism by all-to-all head scatter.

The second sequence-parallel strategy SURVEY §5 names for long-context
training (alongside ring attention, ops/ring_attention.py): instead of
rotating KV chunks around a ring, one ``all_to_all`` re-shards the activations
from sequence-sharded to HEAD-sharded, each device runs ordinary full-sequence
attention over its H/sp heads, and a second ``all_to_all`` restores sequence
sharding. Communication is two all-to-alls of the activations per layer
(DeepSpeed-Ulysses' cost model) versus ring's sp−1 KV-chunk hops; it wins
when heads ≥ sequence shards and the interconnect favors bulk all-to-all
(TPU ICI does).

Constraints (checked): S, H, and K (kv heads) must all divide by sp. GQA
grouping survives the scatter because contiguous blocks of H/sp query heads
map exactly onto blocks of K/sp kv heads.

Gradients flow through shard_map/all_to_all, so the same function serves the
learner's forward and backward; ``jax.checkpoint`` composes around it.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, PartitionSpec as P

from distrl_llm_tpu.ops.attention import attention

# jax.shard_map is the promoted (>= 0.6) spelling; older jax ships it in
# experimental only — same drift class as pltpu.CompilerParams (CI triage)
try:
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def _ulysses_local(q, k, v, kv_valid, *, axis_name: str, sp: int, scale: float,
                   local_impl: str):
    """Per-shard body. q [B, c, H, D], k/v [B, c, K, D], kv_valid [B, c]
    (c = S/sp) → [B, c, H, D]."""
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # seq-sharded → head-sharded: [B, c, H, D] → [B, S, H/sp, D]
    q = a2a(q, split_axis=2, concat_axis=1)
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    valid = jax.lax.all_gather(kv_valid, axis_name, axis=1, tiled=True)  # [B, S]
    # the per-device full-sequence attention goes through the dispatching
    # front door so long-context runs use the O(S)-memory Pallas kernels
    # (splash: native GQA) — materializing [*, S, S] logits here would defeat
    # the sequence parallelism exactly at the lengths it exists for; the
    # reference fallback (CPU tests) builds the dense causal mask itself
    out = attention(q, k, v, None, scale=scale, impl=local_impl, key_valid=valid)
    # head-sharded → seq-sharded: [B, S, H/sp, D] → [B, c, H, D]
    return a2a(out, split_axis=1, concat_axis=2)


def ulysses_attention(
    q: jax.Array,  # [B, S, H, D] global
    k: jax.Array,  # [B, S, K, D]
    v: jax.Array,  # [B, S, K, D]
    key_valid: jax.Array,  # [B, S] 1 = real token
    *,
    mesh: Mesh,
    scale: float | None = None,
    axis_name: str = "sp",
    batch_axis: str | None = "dp",
    local_impl: str = "auto",  # per-device attention: auto | splash | flash | reference
) -> jax.Array:
    """Causal GQA self-attention, sequence-parallel via head scatter.

    Semantics match ``attention_reference(q, k, v,
    causal_padding_mask(key_valid, S))`` up to f32 accumulation order.
    """
    if local_impl == "auto":
        # splash (native GQA, O(S) memory) on TPU; the dense reference off it
        local_impl = "splash" if jax.default_backend() == "tpu" else "reference"
    sp = mesh.shape[axis_name]
    b, s, h, _ = q.shape
    kh = k.shape[2]
    if s % sp != 0:
        raise ValueError(f"sequence {s} not divisible by sp={sp}")
    if h % sp != 0 or kh % sp != 0:
        raise ValueError(
            f"heads must divide by sp for ulysses: H={h}, K={kh}, sp={sp} "
            "(use ring attention when they don't)"
        )
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b_ax = batch_axis
    if b_ax is not None and (
        b_ax not in mesh.shape or b % mesh.shape[b_ax] != 0
    ):
        b_ax = None
    body = partial(_ulysses_local, axis_name=axis_name, sp=sp, scale=scale,
                   local_impl=local_impl)
    seq_spec = P(b_ax, axis_name, None, None)
    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(b_ax, axis_name)),
        out_specs=seq_spec,
    )(q, k, v, key_valid)
