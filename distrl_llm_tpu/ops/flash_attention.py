"""Pallas flash attention for the training/prefill path (N1/N3 equivalent).

Wraps jaxlib's Pallas TPU flash-attention kernel (differentiable: custom-VJP
fwd+bwd kernels) behind the same ``(q, k, v, mask, scale)`` interface as
``attention_reference``, so ``attention(..., impl="flash")`` swaps the O(S²)
XLA softmax for the O(S)-memory blockwise kernel. This is what makes 4k+
long-CoT learner forwards (BASELINE config 4) fit: at S=4k the reference path
materializes [B, H, S, S] f32 logits (~1 GB per layer at B=8), flash keeps
only block-sized tiles in VMEM.

Interface contract (checked, falls back to the XLA path via
``NotImplementedError`` otherwise — see ops/attention.py):

* self-attention with ``Sq == Sk`` and a causal+key-padding mask of the form
  produced by ``causal_padding_mask(attention_mask, q_len=S, q_offset=0)`` —
  the key-validity vector is recovered from the mask's last query row;
* TPU backend only (the kernel is Mosaic-compiled).

Sequence lengths are padded up to the kernel's block multiple with
segment-id-0 rows, which the segment mask excludes from every real token's
attention window.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_BLOCK = 128  # kernel block granularity; seq is padded up to a multiple


@functools.cache
def _kernel():
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    return fa


def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, K, D]
    v: jax.Array,  # [B, Sk, K, D]
    mask: jax.Array | None,  # [B, 1, Sq, Sk] from causal_padding_mask
    scale: float | None = None,
    key_valid: jax.Array | None = None,  # [B, Sk]; preferred over mask
) -> jax.Array:
    if jax.default_backend() != "tpu":
        raise NotImplementedError("flash attention requires the TPU backend")
    b, sq, h, d = q.shape
    sk, kh = k.shape[1], k.shape[2]
    if sq != sk:
        raise NotImplementedError("flash path expects self-attention (Sq == Sk)")
    if mask is not None and mask.shape[1] != 1:
        raise NotImplementedError("flash path expects a head-agnostic mask")
    fa = _kernel()
    if scale is None:
        scale = d**-0.5

    # GQA → MHA for the kernel's equal-head contract. The repeat costs G× KV
    # VMEM traffic only inside the (remat'd) training forward — the decode hot
    # loop never takes this path.
    if kh != h:
        k = jnp.repeat(k, h // kh, axis=2)
        v = jnp.repeat(v, h // kh, axis=2)

    if key_valid is not None:
        # direct [B, Sk] contract — no dense mask was ever materialized
        valid = key_valid.astype(jnp.int32)
    elif mask is not None:
        # legacy contract: key validity from the mask's last query row (with
        # causal ∧ padding and q_offset=0, row S-1 attends exactly the valid keys)
        valid = mask[:, 0, -1, :].astype(jnp.int32)  # [B, Sk]
    else:
        valid = jnp.ones((b, sk), jnp.int32)

    pad = (-sq) % _BLOCK
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    s = sq + pad

    # kernel layout [B, H, S, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    seg = fa.SegmentIds(q=valid, kv=valid)

    block = min(_BLOCK, s)
    sizes = fa.BlockSizes(
        block_q=block, block_k_major=block, block_k=block, block_b=1,
        block_q_major_dkv=block, block_k_major_dkv=block,
        block_k_dkv=block, block_q_dkv=block,
        block_k_major_dq=block, block_k_dq=block, block_q_dq=block,
    )
    out = fa.flash_attention(
        qt, kt, vt, segment_ids=seg, causal=True, sm_scale=scale,
        block_sizes=sizes,
    )
    out = out.transpose(0, 2, 1, 3)  # [B, S, H, D]
    if pad:
        out = out[:, :sq]
    return out.astype(q.dtype)
