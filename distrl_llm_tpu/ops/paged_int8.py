"""Corrected paged-attention launches: compact int8 scales + GQA block fix.

This module assembles jaxlib's Pallas TPU paged-attention kernel function
(``paged_flash_attention_kernel_inline_seq_dim`` — a public dependency,
reused like any library op) with a launch configuration that fixes two
defects of the public ``paged_attention`` wrapper in the pinned jaxlib:

1. **Broadcast scales** (int8 KV): the wrapper broadcasts QuantizedTensor
   scales [K, P, ps, 1] → [K, P, ps, head_dim] f32 BEFORE its pallas_call
   (paged_attention_kernel.py:422), materializing a full-cache-sized f32
   array in HBM on every decode step — per-element traffic becomes 1 (int8)
   + 4 (scales) = 5 bytes vs bf16's 2, NEGATING the int8 bandwidth win.
   The kernel itself never needed the broadcast: its per-page DMA
   descriptor slices whatever scale shape it is given, and the in-VMEM
   dequantize is a broadcasting multiply. We ship scales compact —
   [K, P, ps, 1] f32 in HBM, [2, blk, ps, 1] VMEM scratch (per-element
   traffic 1 + 4/head_dim ≈ 1.03 bytes).

2. **Broken m/l output block specs** (first observed on real silicon,
   round 3): the wrapper reuses the q block spec — whose last-dim block is
   ``head_dim`` — for the running-max/denominator outputs, whose arrays
   have last dim 1. Mosaic's block-shape check ("last two block dims
   divisible by (8, 128) or equal to the array dims") rejects that
   whenever ``head_dim`` is not a multiple of 128 (e.g. Qwen2.5-0.5B's
   head_dim=64, 14q/2kv → 7 groups). The Pallas interpreter never enforces
   the rule, so CPU parity tests pass while the identical launch fails to
   lower on a chip. Our launch gives m/l their own block spec with last-dim
   block 1 — always legal, and the kernel body only ever broadcasts into
   those refs, so numerics are unchanged.

Both the int8 and the plain (bf16/f32) page paths route through the same
corrected launch. An ``interpret`` flag lets CPU tests pin numerics against
the jnp reference without a chip (tools/tpu_kernel_check.py revalidates the
Mosaic lowering on silicon).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.pallas.ops.tpu.paged_attention import quantization_utils

from distrl_llm_tpu.ops.paged_native import CompilerParams
from jax.experimental.pallas.ops.tpu.paged_attention.paged_attention_kernel import (
    DEFAULT_MASK_VALUE,
    paged_flash_attention_kernel_inline_seq_dim,
)


def _launch(
    q: jax.Array,  # [B, H, hd]
    k_w: jax.Array,  # [K, P, ps, hd] (int8 or bf16/f32)
    k_s,  # f32 [K, P, ps, 1] or None
    v_w: jax.Array,
    v_s,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pages_per_sequence]
    *,
    pages_per_compute_block: int,
    mask_value: float,
    interpret: bool,
) -> jax.Array:
    batch_size, num_q_heads, head_dim = q.shape
    num_kv_heads, _, page_size, head_dim_k = k_w.shape
    _, pages_per_sequence = page_indices.shape
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(f"H={num_q_heads} not divisible by K={num_kv_heads}")
    if pages_per_sequence % pages_per_compute_block:
        raise ValueError(
            f"pages_per_sequence={pages_per_sequence} not divisible by "
            f"pages_per_compute_block={pages_per_compute_block}"
        )
    num_groups = num_q_heads // num_kv_heads

    if num_groups % 8 != 0:
        # same layout hint as the jaxlib wrapper: a [1, G, hd] block would
        # get an <8x128> memref layout and fail to lower
        q = q.reshape(batch_size, num_q_heads, 1, head_dim)
        q_block_spec = pl.BlockSpec(
            (None, num_groups, None, head_dim),
            lambda core_index, b, h, *_: (b, h, 0, 0),
        )
        # m/l arrays are [B, H, 1, 1]: last-dim block must be 1, not head_dim
        lm_block_spec = pl.BlockSpec(
            (None, num_groups, None, 1),
            lambda core_index, b, h, *_: (b, h, 0, 0),
        )
        q_dtype_for_kernel_launch = jnp.float32
    else:
        q_block_spec = pl.BlockSpec(
            (None, num_groups, head_dim),
            lambda core_index, b, h, *_: (b, h, 0),
        )
        # m/l arrays are [B, H, 1]
        lm_block_spec = pl.BlockSpec(
            (None, num_groups, 1),
            lambda core_index, b, h, *_: (b, h, 0),
        )
        q_dtype_for_kernel_launch = q.dtype

    grid = (1, batch_size, num_kv_heads)  # no megacore
    quantized = k_s is not None
    in_specs = [
        q_block_spec,
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY) if quantized else None,
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY) if quantized else None,
    ]
    # int8 scale buffers stay at their stored [ps, 1] shape instead of a
    # broadcast [ps, head_dim]
    scratch_shapes = (
        pltpu.VMEM(
            (2, pages_per_compute_block, page_size, head_dim), k_w.dtype
        ),
        pltpu.VMEM((2, pages_per_compute_block, page_size, 1), k_s.dtype)
        if quantized
        else None,
        pltpu.VMEM(
            (2, pages_per_compute_block, page_size, head_dim), v_w.dtype
        ),
        pltpu.VMEM((2, pages_per_compute_block, page_size, 1), v_s.dtype)
        if quantized
        else None,
        # ONE shared DMA semaphore, matching the pinned jaxlib's kernel
        # signature (17 positional args): the K and V copy descriptors both
        # signal/wait it. Older jaxlibs took separate K/V semaphore arrays —
        # the signature drift that had these launches env-skipped (ISSUE 15
        # satellite; the drift PR 3 fixed for the other 16 tests).
        pltpu.SemaphoreType.DMA,
    )

    operands = (
        lengths,
        page_indices.reshape(-1),
        jnp.zeros((1,), jnp.int32),  # buffer index
        jnp.zeros((1,), jnp.int32),  # step (0 prefetches the first block)
        q.astype(q_dtype_for_kernel_launch),
        k_w,
        k_s,  # None when unquantized — matches the None in_spec/scratch
        v_w,
        v_s,
    )
    out, _, _ = pl.pallas_call(
        functools.partial(
            paged_flash_attention_kernel_inline_seq_dim,
            pages_per_sequence=pages_per_sequence,
            batch_size=batch_size,
            pages_per_compute_block=pages_per_compute_block,
            mask_value=mask_value,
            attn_logits_soft_cap=None,
            megacore_mode=None,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            in_specs=in_specs,
            out_specs=[q_block_spec, lm_block_spec, lm_block_spec],
            grid=grid,
            scratch_shapes=scratch_shapes,
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q_dtype_for_kernel_launch),
            jax.ShapeDtypeStruct((*q.shape[:-1], 1), jnp.float32),
            jax.ShapeDtypeStruct((*q.shape[:-1], 1), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    return out.reshape(batch_size, num_q_heads, head_dim).astype(q.dtype)


def paged_attention_int8(
    q: jax.Array,  # [B, H, hd]
    k_pages,  # QuantizedTensor: weight int8 [K, P, ps, hd], scales [K, P, ps, 1]
    v_pages,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pages_per_sequence]
    *,
    pages_per_compute_block: int = 4,
    mask_value: float = DEFAULT_MASK_VALUE,
    interpret: bool = False,
) -> jax.Array:
    """GQA paged decode attention over int8 pages with COMPACT scales."""
    assert isinstance(k_pages, quantization_utils.QuantizedTensor)
    assert isinstance(v_pages, quantization_utils.QuantizedTensor)
    return _launch(
        q,
        k_pages.weight,
        k_pages.scales,
        v_pages.weight,
        v_pages.scales,
        lengths,
        page_indices,
        pages_per_compute_block=pages_per_compute_block,
        mask_value=mask_value,
        interpret=interpret,
    )


def paged_attention_gqa(
    q: jax.Array,  # [B, H, hd]
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pages_per_sequence]
    *,
    pages_per_compute_block: int = 4,
    mask_value: float = DEFAULT_MASK_VALUE,
    interpret: bool = False,
) -> jax.Array:
    """GQA paged decode attention over plain pages, corrected launch.

    Identical numerics to jaxlib's ``paged_attention`` wrapper, but lowers
    for every (num_groups, head_dim) combination — the wrapper's m/l block
    specs reject head_dim not divisible by 128 (see module docstring)."""
    return _launch(
        q,
        k_pages,
        None,
        v_pages,
        None,
        lengths,
        page_indices,
        pages_per_compute_block=pages_per_compute_block,
        mask_value=mask_value,
        interpret=interpret,
    )
