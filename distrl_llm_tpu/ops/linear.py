"""Linear projection with pluggable weight containers.

All model matmuls route through ``linear`` so the frozen base can swap its
weights for quantized containers (int8/int4 weight-only — the N4 equivalent of
the reference's bitsandbytes NF4 base, distributed_actor.py:17) without
touching model code. Quantized containers live in ops/quant.py and are
registered pytrees, so they flow through jit/pjit/scan like arrays.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def linear(x: jax.Array, w, b: jax.Array | None = None) -> jax.Array:
    """y = x @ w (+ b). ``w`` is either a plain [in, out] array or a quantized
    container dict (ops/quant.py): {"q": [G, g, out], "scale": [G, 1, out]}.

    Quantized containers dispatch to the fused Pallas dequant-matmul
    (ops/quant_matmul.py) when it is enabled for this backend
    (DISTRL_QUANT_MATMUL; probe-gated "auto" = TPU only), else to the XLA
    container path below — same math, same order, greedy-bit-identical."""
    if isinstance(w, dict):
        if w["q"].ndim == 3:
            from distrl_llm_tpu.ops.quant_matmul import (
                dispatch_choices, quant_matmul, quant_matmul_dispatch,
            )

            bits = 4 if w["q"].dtype == jnp.int4 else 8
            use, interp = quant_matmul_dispatch(
                w["q"].shape, bits, 0, x.shape[-1], x.dtype
            )
            dispatch_choices[(bits, x.shape[-1], w["q"].shape[-1], 0)] = (
                "kernel" if use else "xla"
            )
            if use:
                return quant_matmul(x, w, b, interpret=interp)
        # dequant folded into the matmul: XLA fuses the convert+scale into
        # the MXU operand read, so the weight moves through HBM at int8/int4
        # width (the N4 dequant-matmul — the fused kernel's exact-fallback)
        # q·scale in f32 (scale is stored f32 — bf16-rounding the scales
        # would stack ~0.4% error on the quantization error), cast once
        wq = (w["q"].astype(jnp.float32) * w["scale"]).astype(x.dtype)
        G, g, d_out = wq.shape[-3:]
        y = jnp.einsum("...i,io->...o", x, wq.reshape(G * g, d_out))
    else:
        y = jnp.einsum("...i,io->...o", x, w)
    if b is not None:
        y = y + b
    return y


def lora_delta(
    x: jax.Array, a: jax.Array, b: jax.Array, scale,
    dropout_rate: float = 0.0, dropout_rng: jax.Array | None = None,
) -> jax.Array:
    """LoRA contribution (x @ A) @ B · scale, computed in the activation dtype.
    A: [in, r], B: [r, out], scale = alpha / r (rsLoRA off — helper.py:44).
    Factors stored at higher precision (f32 LoRA over a bf16 base) are cast to
    the activation dtype so the delta never widens the residual stream.

    ``dropout_rate`` + ``dropout_rng`` enable peft-style LoRA dropout: the
    adapter INPUT is dropped (inverted scaling), the base path is untouched —
    matching ``lora_dropout`` in the reference's init_peft_model
    (helper.py:40). Inference callers pass no rng and pay nothing."""
    a = a.astype(x.dtype)
    b = b.astype(x.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, x.shape)
        x = jnp.where(keep, x / (1.0 - dropout_rate), 0.0).astype(x.dtype)
    return (x @ a @ b) * jnp.asarray(scale, dtype=x.dtype)
