"""Attention ops: masked GQA attention with a plain-XLA reference path.

This is the N1/N3-equivalent compute core (SURVEY §2b): the reference gets its
attention from vLLM's CUDA kernels (decode) and Triton (train); here the
baseline is a jnp implementation XLA fuses well on the MXU, with Pallas flash
attention layered on top (ops/flash_attention.py) for long sequences, selected
by ``attention(..., impl=...)``.

Shapes follow the TPU-friendly layout [batch, seq, heads, head_dim] — last two
dims map onto (sublane, lane) tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative for masked logits; avoids NaNs from true -inf


def repeat_kv(k: jax.Array, num_groups: int) -> jax.Array:
    """[B, S, K, D] → [B, S, K*num_groups, D] by repeating each kv head for its
    query group (GQA)."""
    if num_groups == 1:
        return k
    b, s, kh, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kh, num_groups, d)).reshape(
        b, s, kh * num_groups, d
    )


def attention_reference(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, K, D]
    v: jax.Array,  # [B, Sk, K, D]
    mask: jax.Array | None,  # broadcastable to [B, H, Sq, Sk]; True = attend
    scale: float | None = None,
) -> jax.Array:
    """Plain-XLA masked attention. Softmax in f32 regardless of input dtype."""
    num_groups = q.shape[2] // k.shape[2]
    k = repeat_kv(k, num_groups)
    v = repeat_kv(v, num_groups)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_padding_mask(
    attention_mask: jax.Array,  # [B, Sk] 1 = real token
    q_len: int,
    q_offset: jax.Array | int = 0,
) -> jax.Array:
    """[B, 1, Sq, Sk] boolean mask combining causality with key padding.

    ``q_offset`` is the absolute position of the first query row — 0 for a
    training/prefill forward, the current decode length for single-token decode
    steps against a KV cache.
    """
    sk = attention_mask.shape[-1]
    q_pos = q_offset + jnp.arange(q_len)[:, None]  # [Sq, 1]
    k_pos = jnp.arange(sk)[None, :]  # [1, Sk]
    causal = k_pos <= q_pos  # [Sq, Sk]
    pad = attention_mask[:, None, None, :].astype(bool)  # [B, 1, 1, Sk]
    return causal[None, None, :, :] & pad


_flash_fallback_warned = False


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None,
    scale: float | None = None,
    impl: str = "reference",
) -> jax.Array:
    """Dispatching front door. ``impl``: "reference" (XLA) or "flash" (Pallas,
    TPU only; warns once and falls back to reference where unsupported)."""
    if impl == "flash":
        try:
            from distrl_llm_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, mask, scale=scale)
        except (ImportError, NotImplementedError) as e:
            global _flash_fallback_warned
            if not _flash_fallback_warned:
                _flash_fallback_warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "flash attention unavailable (%s); falling back to the XLA "
                    "reference path — O(Sq*Sk) memory", e,
                )
    return attention_reference(q, k, v, mask, scale=scale)
