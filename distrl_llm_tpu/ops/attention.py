"""Attention ops: masked GQA attention with a plain-XLA reference path.

This is the N1/N3-equivalent compute core (SURVEY §2b): the reference gets its
attention from vLLM's CUDA kernels (decode) and Triton (train); here the
baseline is a jnp implementation XLA fuses well on the MXU, with Pallas flash
attention layered on top (ops/flash_attention.py) for long sequences, selected
by ``attention(..., impl=...)``.

Shapes follow the TPU-friendly layout [batch, seq, heads, head_dim] — last two
dims map onto (sublane, lane) tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # large-negative for masked logits; avoids NaNs from true -inf


def repeat_kv(k: jax.Array, num_groups: int) -> jax.Array:
    """[B, S, K, D] → [B, S, K*num_groups, D] by repeating each kv head for its
    query group (GQA)."""
    if num_groups == 1:
        return k
    b, s, kh, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, kh, num_groups, d)).reshape(
        b, s, kh * num_groups, d
    )


def attention_reference(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, K, D]
    v: jax.Array,  # [B, Sk, K, D]
    mask: jax.Array | None,  # [B, 1|H, Sq, Sk]; True = attend
    scale: float | None = None,
) -> jax.Array:
    """Plain-XLA masked attention. Softmax in f32 regardless of input dtype.

    GQA contracts the grouped query heads [B, Sq, K, G, D] directly against the
    K kv heads — never materializing ``repeat_kv``, which would multiply KV
    HBM traffic by G (7× for Qwen2.5-0.5B) in the decode hot loop."""
    return _gqa_attention(q, k, v, mask, scale, kv_subscript="bskd", kv_heads_axis=2)


def causal_padding_mask(
    attention_mask: jax.Array,  # [B, Sk] 1 = real token
    q_len: int,
    q_offset: jax.Array | int = 0,
) -> jax.Array:
    """[B, 1, Sq, Sk] boolean mask combining causality with key padding.

    ``q_offset`` is the absolute position of the first query row — 0 for a
    training/prefill forward, the current decode length for single-token decode
    steps against a KV cache.
    """
    sk = attention_mask.shape[-1]
    q_pos = q_offset + jnp.arange(q_len)[:, None]  # [Sq, 1]
    k_pos = jnp.arange(sk)[None, :]  # [1, Sk]
    causal = k_pos <= q_pos  # [Sq, Sk]
    pad = attention_mask[:, None, None, :].astype(bool)  # [B, 1, 1, Sk]
    return causal[None, None, :, :] & pad


def attention_cached(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, K, D, Sk] — decode-cache layout, S minormost
    v: jax.Array,  # [B, K, D, Sk]
    mask: jax.Array | None,  # [B, 1|H, Sq, Sk]; True = attend
    scale: float | None = None,
    formulation: str = "dot",
) -> jax.Array:
    """Masked GQA attention against a [B, K, D, S] KV cache.

    The cache keeps S as its minormost dim — the layout XLA's layout
    assignment picks for the decode while-loop. Storing the cache any other
    way makes XLA insert full-cache conversion copies inside the loop (two
    extra cache-sized HBM temps that break donation aliasing).

    ``formulation="mulred"`` switches the Sq==1 decode read from
    ``dot_general`` to multiply+reduce — required inside K-steps-per-dispatch
    scan programs, where ANY dot over the carried cache makes TPU layout
    assignment relayout the operand to a B-minormost layout with a
    cache-leaf-sized conversion copy per leaf per iteration, defeating
    in-place aliasing and OOMing the program (r5 silicon finding; the
    9-variant ladder in tools/chunk_alias_bisect.py isolates it — operand
    order and which einsum are irrelevant, only mul+reduce keeps the native
    layout). Reduce-of-product fuses into the cache read, so HBM traffic is
    identical; the MXU is ~idle at one query token either way. Sq>1 calls
    (prefill) always use the dot path."""
    return _gqa_attention(q, k, v, mask, scale, kv_subscript="bkds",
                          kv_heads_axis=1, formulation=formulation)


def quantize_kv_position(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(B, K, position) symmetric int8 over head_dim for the decode
    cache: [B, K, hd, S] → (int8 [B, K, hd, S], f32 scales [B, K, 1, S])."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=2, keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q8 = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127, 127).astype(jnp.int8)
    return q8, s


def attention_cached_quant(
    q: jax.Array,  # [B, Sq, H, D]
    k8: jax.Array,  # int8 [B, K, D, Sk] — decode-cache layout
    k_scale: jax.Array,  # f32 [B, K, 1, Sk]
    v8: jax.Array,  # int8 [B, K, D, Sk]
    v_scale: jax.Array,  # f32 [B, K, 1, Sk]
    mask: jax.Array | None,
    scale: float | None = None,
    formulation: str = "dot",
) -> jax.Array:
    """Masked GQA attention against an int8 KV cache with per-position
    scales, dequantization FOLDED into the attention math so the cache is
    read from HBM at 1 byte/element (the paged engine's int8-KV bandwidth
    win, for the dense engine):

    * k: logits[..., s] = (Σ_d q·k8) · k_scale[s] — the scale factors out
      of the contraction over d;
    * v: out[..., d] = Σ_s probs·v8·v_scale[s] — the scale rides the probs
      ([B, K, G, Sq, Sk] f32, already materialized by the softmax).

    XLA fuses the int8→f32 convert into the dot-operand read; the
    decode-step HBM audit in tools/tpu_kernel_check.py is the on-chip
    check that no f32 cache-sized temp materializes.

    ``formulation="mulred"`` — see attention_cached: mandatory for the
    scan-chunk programs, where a dot over the carried int8 cache costs a
    per-leaf relayout copy per iteration."""
    return _gqa_attention(
        q, k8, v8, mask, scale, kv_subscript="bkds", kv_heads_axis=1,
        k_scale=k_scale, v_scale=v_scale, formulation=formulation,
    ).astype(q.dtype)


def _gqa_attention(q, k, v, mask, scale, *, kv_subscript: str,
                   kv_heads_axis: int, k_scale=None, v_scale=None,
                   formulation: str = "dot"):
    """Shared GQA attention body; only the kv einsum layout differs between
    the training ([B,S,K,D]) and decode-cache ([B,K,D,S]) paths.

    ``k_scale``/``v_scale`` ([B, K, 1, Sk] f32, decode-cache layout only)
    switch on the fused-dequant int8 path: k/v stay int8 in HBM, the k
    scale factors out of the d-contraction onto the logits, the v scale
    rides the (already f32) probs."""
    if formulation not in ("dot", "mulred"):
        # a typo ('mul_red', 'dot_general', …) must not silently take the
        # dot path — inside a scan program that reintroduces the per-leaf
        # relayout copy / OOM the flag exists to avoid (ADVICE r5)
        raise ValueError(
            f"formulation must be 'dot' or 'mulred', got {formulation!r}"
        )
    quant = k_scale is not None
    assert not quant or kv_heads_axis == 1, "scales imply the [B,K,D,S] layout"
    b, sq, h, d = q.shape
    kh = k.shape[kv_heads_axis]
    g = h // kh
    if scale is None:
        scale = d**-0.5
    if formulation == "mulred" and sq == 1 and kv_heads_axis == 1:
        return _gqa_mulred(q, k, v, mask, scale, k_scale=k_scale,
                           v_scale=v_scale)
    qg = q.reshape(b, sq, kh, g, d)
    if quant:
        qg = qg.astype(jnp.float32)
        k = k.astype(jnp.float32)  # fused into the dot-operand read by XLA
    logits = jnp.einsum(
        f"bqkgd,{kv_subscript}->bkgqs", qg, k, preferred_element_type=jnp.float32
    )
    if quant:
        logits = logits * k_scale[:, :, :, None, :]  # [B, K, 1, 1, Sk]
    logits = logits * scale
    if mask is not None:
        if mask.shape[1] == 1:  # head-agnostic mask
            m = mask[:, :, None]  # [B, 1, 1, Sq, Sk]
        else:
            m = mask.reshape(b, kh, g, *mask.shape[2:])
        logits = jnp.where(m, logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if quant:
        probs = probs * v_scale[:, :, :, None, :]
        v = v.astype(jnp.float32)
    else:
        probs = probs.astype(v.dtype)
    out = jnp.einsum(f"bkgqs,{kv_subscript}->bqkgd", probs, v)
    return out.reshape(b, sq, h, d)


def mulred_broadcast_bytes(batch_rows: int, kv_heads: int, groups: int,
                           head_dim: int, kv_len: int) -> int:
    """Bytes of ONE layer's unfused ``_gqa_mulred`` broadcast product — the
    [B, KH, G, D, S] f32 temp a backend would materialize if it failed to
    fuse reduce-of-product into the cache read. The HBM audits
    (tools/tpu_kernel_check.py and ``compile_chunk_guarded``'s
    ``fusion_bytes`` threshold) price temp bytes against this: a fused
    program's scratch sits far below it, an unfused one lands on it and
    OOMs real geometries (ADVICE r5)."""
    return batch_rows * kv_heads * groups * head_dim * kv_len * 4


def _gqa_mulred(q, k, v, mask, scale, *, k_scale=None, v_scale=None):
    """Sq==1 decode attention as multiply+reduce over the [B, K, D, S]
    cache — no ``dot_general`` touches the cache operands, so TPU layout
    assignment keeps the carry's native S-minormost layout inside scan
    programs instead of inserting cache-sized relayout copies each
    iteration (attention_cached's docstring has the full story). Both
    contractions accumulate in f32 (the dot path's k-side did too via
    preferred_element_type; the v-side rounded at bf16 — mulred is the
    same or slightly better numerically). XLA fuses reduce-of-product
    into the cache read: one pass over K + one over V, the same HBM
    traffic as the dot formulation."""
    quant = k_scale is not None
    b, _, h, d = q.shape
    kh = k.shape[1]
    g = h // kh
    qv = q.reshape(b, kh, g, d).astype(jnp.float32)
    # logits[b,k,g,s] = sum_d q[b,k,g,d] * K[b,k,d,s]
    logits = jnp.sum(qv[..., None] * k.astype(jnp.float32)[:, :, None], axis=-2)
    if quant:
        logits = logits * k_scale[:, :, None, 0, :]  # [B, K, 1, Sk]
    logits = logits * scale
    if mask is not None:  # [B, 1|H, 1, Sk]
        m = (
            mask[:, :, None, 0, :]  # head-agnostic -> [B, 1, 1, Sk]
            if mask.shape[1] == 1
            else mask[:, :, 0, :].reshape(b, kh, g, mask.shape[-1])
        )
        logits = jnp.where(m, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)  # [B, K, G, Sk] f32
    if quant:
        probs = probs * v_scale[:, :, None, 0, :]
    # out[b,k,g,d] = sum_s probs[b,k,g,s] * V[b,k,d,s]
    out = jnp.sum(probs[:, :, :, None, :] * v.astype(jnp.float32)[:, :, None],
                  axis=-1)
    return out.reshape(b, 1, h, d).astype(q.dtype)


import logging

logger = logging.getLogger(__name__)

_flash_fallback_warned = False
_kernel_probe_state: dict = {}

# substrings marking transient device/runtime failures that say nothing
# about lowering legality — never negative-cache these
_TRANSIENT_ERR_MARKS = ("RESOURCE_EXHAUSTED", "DEADLINE", "UNAVAILABLE",
                        "CANCELLED", "ABORTED")


def _kernel_lowers(kind: str, h: int, kh: int, d: int, sq: int, dtype) -> bool:
    """Probe-compile the flash/splash kernel — forward AND backward — at
    this (head geometry, seq) config, once per config. Mosaic block-rule
    rejections fire at COMPILE time — past any try/except around the
    traced call inside a larger jit, which is exactly how the paged launch
    failed on first silicon (round 3; see ops/paged_int8.py). An eager
    probe catches them while the reference-path fallback is still
    possible. The seq is part of the key because block shapes derive from
    it (splash: block = min(512, padded seq)); the grad pass covers the
    custom-VJP dkv/dq kernels the training path differentiates through."""
    key = (kind, h, kh, d, sq, jnp.dtype(dtype).name)
    if key not in _kernel_probe_state:
        try:
            b = 1
            q = jnp.zeros((b, sq, h, d), dtype)
            k = jnp.zeros((b, sq, kh, d), dtype)
            if kind == "flash":
                from distrl_llm_tpu.ops.flash_attention import flash_attention

                fwd = lambda q_, k_: flash_attention(q_, k_, k_, None)  # noqa: E731
            else:
                from distrl_llm_tpu.ops.splash import splash_attention

                valid = jnp.ones((b, sq), jnp.int32)
                fwd = lambda q_, k_: splash_attention(q_, k_, k_, valid)  # noqa: E731
            jax.block_until_ready(fwd(q, k))
            # backward kernels (dq/dkv block specs) lower independently
            g = jax.grad(lambda q_, k_: fwd(q_, k_).astype(jnp.float32).sum(),
                         argnums=(0, 1))(q, k)
            jax.block_until_ready(g)
            _kernel_probe_state[key] = True
        except Exception as e:  # noqa: BLE001 — classify before caching
            msg = str(e).upper()
            transient = any(m in msg for m in _TRANSIENT_ERR_MARKS)
            if not transient:
                _kernel_probe_state[key] = False
            logger.warning(
                "%s attention kernel failed its lowering probe for %s (%s); "
                "using the XLA reference path%s", kind, key, e,
                " (transient error — will re-probe)" if transient else "",
            )
            return False
    return _kernel_probe_state[key]


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None,
    scale: float | None = None,
    impl: str = "reference",
    key_valid: jax.Array | None = None,
) -> jax.Array:
    """Dispatching front door. ``impl``: "reference" (XLA) or "flash" (Pallas,
    TPU only; warns once and falls back to reference where unsupported).

    ``key_valid`` is the [B, Sk] validity vector; the flash/splash paths
    consume it directly (no [B, 1, Sq, Sk] mask needs to exist). When only
    ``key_valid`` is given and the fallback runs, the dense causal mask is
    built here."""
    global _flash_fallback_warned
    h, kh, d = q.shape[2], k.shape[2], q.shape[3]
    if impl == "splash":
        try:
            if jax.default_backend() != "tpu":
                raise NotImplementedError(
                    "splash kernel requires the TPU backend (interpret mode "
                    "is test-only)"
                )
            if not _kernel_lowers("splash", h, kh, d, q.shape[1], q.dtype):
                raise NotImplementedError("splash failed its lowering probe")
            from distrl_llm_tpu.ops.splash import splash_attention

            return splash_attention(q, k, v, key_valid, scale=scale)
        except Exception as e:  # noqa: BLE001 — fall back with one warning
            if not _flash_fallback_warned:
                _flash_fallback_warned = True
                logger.warning(
                    "splash attention unavailable (%s); falling back to the "
                    "XLA reference path", e,
                )
    if impl == "flash":
        try:
            if jax.default_backend() == "tpu" and not _kernel_lowers(
                "flash", h, kh, d, q.shape[1], q.dtype
            ):
                raise NotImplementedError("flash failed its lowering probe")
            from distrl_llm_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, mask, scale=scale, key_valid=key_valid)
        except (ImportError, NotImplementedError) as e:
            if not _flash_fallback_warned:
                _flash_fallback_warned = True
                logger.warning(
                    "flash attention unavailable (%s); falling back to the XLA "
                    "reference path — O(Sq*Sk) memory", e,
                )
    if mask is None and key_valid is not None:
        mask = causal_padding_mask(key_valid, q_len=q.shape[1])
    return attention_reference(q, k, v, mask, scale=scale)
