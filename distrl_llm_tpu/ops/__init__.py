from distrl_llm_tpu.ops.attention import (  # noqa: F401
    attention,
    attention_reference,
    causal_padding_mask,
    repeat_kv,
)
from distrl_llm_tpu.ops.linear import linear, lora_delta  # noqa: F401
