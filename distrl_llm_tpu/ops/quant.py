"""Weight-only int8/int4 quantization for the frozen base model.

TPU-native equivalent of the reference's bitsandbytes NF4 base weights
(LOAD_IN_4BIT at distributed_actor.py:17, the ``*-bnb-4bit`` checkpoints at
train_distributed.py:11 — SURVEY §2b N4). Instead of CUDA dequant kernels:

* a quantized weight is a plain dict ``{"q": int8|int4 [..., G, g, out],
  "scale": f32 [..., G, 1, out]}`` — groupwise symmetric absmax over the
  input dim (bnb's NF4 uses 64-wide blocks; same knob here). Plain dicts
  flow through jit/scan/tree_map/sharding exactly like arrays, so the model
  and partition code need no special cases beyond ``ops.linear``.
* dequantization is ``q * scale`` folded into the consuming matmul — XLA
  fuses the convert+multiply into the MXU operand read, so HBM traffic drops
  by the storage ratio (2× int8, 4× int4) with no custom kernel. (A Pallas
  dequant-matmul is the escalation path if profiling ever shows the fusion
  breaking.)

Only the per-layer projection weights are quantized; embeddings, lm_head,
norms, and biases stay in the working dtype (mirrors bnb, which quantizes
nn.Linear only).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# projection weights eligible for quantization (helper.py:29–37 targets — the
# same set LoRA adapts, which is every linear in the decoder layer)
QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "scale" in w


def is_quantized_tree(params) -> bool:
    """True if any layer projection in the param tree is a quantized
    container (full fine-tuning must refuse these — int payloads have no
    gradients)."""
    layers = params.get("layers", {}) if isinstance(params, dict) else {}
    return any(is_quantized(w) for w in layers.values())


def quantize(w: jax.Array, bits: int = 8, group_size: int | None = None) -> Params:
    """Quantize [..., in, out] → {"q": [..., G, g, out], "scale": [..., G, 1, out]}.

    Symmetric absmax per (group, out-column). ``group_size`` divides the input
    dim; None means one group (pure per-column scales — fine for int8; int4
    wants 64–128 wide groups for accuracy, matching bnb's blockwise NF4).
    """
    if bits == 8:
        qmax, dtype = 127.0, jnp.int8
    elif bits == 4:
        qmax, dtype = 7.0, jnp.int4
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    *lead, d_in, d_out = w.shape
    g = group_size or d_in
    if d_in % g != 0:
        raise ValueError(f"group_size {g} does not divide input dim {d_in}")
    grouped = w.astype(jnp.float32).reshape(*lead, d_in // g, g, d_out)
    absmax = jnp.max(jnp.abs(grouped), axis=-2, keepdims=True)  # [..., G, 1, out]
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(grouped * inv), -qmax, qmax).astype(dtype)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def dequantize(w: Params, dtype=jnp.bfloat16) -> jax.Array:
    """[..., G, g, out] quantized → [..., in, out] dense in ``dtype``."""
    q, scale = w["q"], w["scale"]
    full = q.astype(jnp.float32) * scale
    *lead, G, g, d_out = full.shape
    return full.reshape(*lead, G * g, d_out).astype(dtype)


def quantize_params(
    params: Params, bits: int = 8, group_size: int | None = None
) -> Params:
    """Quantize a decoder param tree's layer projections in place of their
    bf16 arrays. Embed/lm_head/norms/biases pass through untouched."""
    layers = dict(params["layers"])
    for name in QUANT_TARGETS:
        if name in layers:
            layers[name] = quantize(layers[name], bits=bits, group_size=group_size)
    out = dict(params)
    out["layers"] = layers
    return out


def quant_bits_for(config_value: str) -> int | None:
    """Map the ``base_quant`` config field ({"none","int8","int4"}) to bits."""
    return {"none": None, "int8": 8, "int4": 4}[config_value]


def default_group_size(bits: int) -> int | None:
    """int4 needs blockwise scales for accuracy (bnb uses 64); int8 is fine
    with pure per-column scales."""
    return 64 if bits == 4 else None
