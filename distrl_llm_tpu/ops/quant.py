"""Weight-only int8/int4 quantization for the frozen base model.

TPU-native equivalent of the reference's bitsandbytes NF4 base weights
(LOAD_IN_4BIT at distributed_actor.py:17, the ``*-bnb-4bit`` checkpoints at
train_distributed.py:11 — SURVEY §2b N4). Instead of CUDA dequant kernels:

* a quantized weight is a plain dict ``{"q": int8|int4 [..., G, g, out],
  "scale": f32 [..., G, 1, out]}`` — groupwise symmetric absmax over the
  input dim (bnb's NF4 uses 64-wide blocks; same knob here). Plain dicts
  flow through jit/scan/tree_map/sharding exactly like arrays, so the model
  and partition code need no special cases beyond ``ops.linear``.
* dequantization is ``q * scale`` folded into the consuming matmul — XLA
  fuses the convert+multiply into the MXU operand read, so HBM traffic drops
  by the storage ratio (2× int8, 4× int4) with no custom kernel. (A Pallas
  dequant-matmul is the escalation path if profiling ever shows the fusion
  breaking.)

Only the per-layer projection weights are quantized; embeddings, lm_head,
norms, and biases stay in the working dtype (mirrors bnb, which quantizes
nn.Linear only).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# projection weights eligible for quantization (helper.py:29–37 targets — the
# same set LoRA adapts, which is every linear in the decoder layer)
QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "scale" in w


def is_quantized_tree(params) -> bool:
    """True if any layer projection in the param tree is a quantized
    container (full fine-tuning must refuse these — int payloads have no
    gradients)."""
    layers = params.get("layers", {}) if isinstance(params, dict) else {}
    return any(is_quantized(w) for w in layers.values())


def quantize(w: jax.Array, bits: int = 8, group_size: int | None = None) -> Params:
    """Quantize [..., in, out] → {"q": [..., G, g, out], "scale": [..., G, 1, out]}.

    Symmetric absmax per (group, out-column). ``group_size`` divides the input
    dim; None means one group (pure per-column scales — fine for int8; int4
    wants 64–128 wide groups for accuracy, matching bnb's blockwise NF4).
    """
    if bits == 8:
        qmax, dtype = 127.0, jnp.int8
    elif bits == 4:
        qmax, dtype = 7.0, jnp.int4
    else:
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    *lead, d_in, d_out = w.shape
    g = group_size or d_in
    if d_in % g != 0:
        raise ValueError(f"group_size {g} does not divide input dim {d_in}")
    grouped = w.astype(jnp.float32).reshape(*lead, d_in // g, g, d_out)
    absmax = jnp.max(jnp.abs(grouped), axis=-2, keepdims=True)  # [..., G, 1, out]
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(grouped * inv), -qmax, qmax).astype(dtype)
    return {"q": q, "scale": scale.astype(jnp.float32)}


def dequantize(w: Params, dtype=jnp.bfloat16) -> jax.Array:
    """[..., G, g, out] quantized → [..., in, out] dense in ``dtype``."""
    q, scale = w["q"], w["scale"]
    full = q.astype(jnp.float32) * scale
    *lead, G, g, d_out = full.shape
    return full.reshape(*lead, G * g, d_out).astype(dtype)


def quantize_params(
    params: Params, bits: int = 8, group_size: int | None = None
) -> Params:
    """Quantize a decoder param tree's layer projections in place of their
    bf16 arrays. Embed/lm_head/norms/biases pass through untouched."""
    layers = dict(params["layers"])
    for name in QUANT_TARGETS:
        if name in layers:
            layers[name] = quantize(layers[name], bits=bits, group_size=group_size)
    out = dict(params)
    out["layers"] = layers
    return out


def pack_int4(q: jax.Array) -> jax.Array:
    """Pack int4 values (range [-8, 7], any int dtype) two-per-byte along
    the group axis (-2) → int8 [..., G, g/2, out].

    The TRANSPORT/STORAGE form of an int4 payload: serialization layers
    that widen jnp.int4 to a full byte store nibbles at their true
    0.5 byte/value width instead (``pack_params_int4`` applies it to a
    whole quantized tree — the bench/prep params disk cache goes through
    it). ``unpack_int4`` is the bit-exact inverse (pinned by
    tests/test_quant.py). Requires an even group dim."""
    q8 = q.astype(jnp.int8)
    g = q8.shape[-2]
    if g % 2 != 0:
        raise ValueError(f"pack_int4 needs an even group dim, got {g}")
    lo = q8[..., 0::2, :] & jnp.int8(0x0F)
    hi = jnp.left_shift(q8[..., 1::2, :] & jnp.int8(0x0F), 4)
    return lo | hi


def unpack_int4(packed: jax.Array, dtype=jnp.int4) -> jax.Array:
    """int8 nibble-packed [..., G, g/2, out] → int4 values [..., G, g, out]
    (sign-extended via arithmetic shifts — bit-exact pack/unpack
    roundtrip)."""
    p8 = packed.astype(jnp.int8)
    lo = jnp.right_shift(jnp.left_shift(p8, 4), 4)  # sign-extend low nibble
    hi = jnp.right_shift(p8, 4)  # arithmetic shift sign-extends high nibble
    *lead, gh, out = p8.shape
    stacked = jnp.stack([lo, hi], axis=-2)  # [..., g/2, 2, out]
    return stacked.reshape(*lead, gh * 2, out).astype(dtype)


def pack_params_int4(params: Params) -> Params:
    """Transport form of a quantized param tree: every int4 container's
    payload is nibble-packed (``{"q4": int8 [..., G, g/2, out], "scale"}``
    replaces ``{"q", "scale"}``), halving its serialized bytes. int8
    containers and dense leaves pass through untouched; containers with an
    odd group dim stay unpacked. ``unpack_params_int4`` is the bit-exact
    inverse."""
    layers = dict(params.get("layers", {}))
    for name, w in layers.items():
        if (
            is_quantized(w) and w["q"].dtype == jnp.int4
            and w["q"].shape[-2] % 2 == 0
        ):
            layers[name] = {"q4": pack_int4(w["q"]), "scale": w["scale"]}
    out = dict(params)
    out["layers"] = layers
    return out


def unpack_params_int4(params: Params) -> Params:
    """Inverse of ``pack_params_int4``: nibble-packed containers return to
    their live ``{"q": int4, "scale"}`` form; everything else passes
    through."""
    layers = dict(params.get("layers", {}))
    for name, w in layers.items():
        if isinstance(w, dict) and "q4" in w:
            layers[name] = {"q": unpack_int4(w["q4"]), "scale": w["scale"]}
    out = dict(params)
    out["layers"] = layers
    return out


def quant_bits_for(config_value: str) -> int | None:
    """Map the ``base_quant`` config field ({"none","int8","int4"}) to bits."""
    return {"none": None, "int8": 8, "int4": 4}[config_value]


def default_group_size(bits: int) -> int | None:
    """int4 needs blockwise scales for accuracy (bnb uses 64); int8 is fine
    with pure per-column scales."""
    return 64 if bits == 4 else None
