"""Fused quantized-matmul Pallas kernel: dequant on the operand read, LoRA in
the epilogue.

Decode is memory-bound (~2% MFU, BENCH_r05 ≈ 4% of the HBM roofline), so
tok/s/chip tracks resident bytes per token almost linearly.  The container
path in ``ops/linear.py`` *hopes* XLA fuses ``(q·scale).astype → einsum`` into
the MXU operand read; this module replaces the hope with a measured kernel for
decode shapes:

* the int8/int4 payload is streamed from HBM at storage width and dequantized
  **in VMEM** per (K-block, N-tile): ``w = (q · scale).astype(x.dtype)`` right
  before the ``jnp.dot`` — the weight never exists at bf16 width in HBM;
* the **LoRA delta rides the epilogue**: ``((x@A)@B)·scale`` is accumulated
  into the same output tile, so the adapter path costs no extra output
  round-trip and no separate kernel launch (the reference runs NF4 base +
  fp16 LoRA as two CUDA paths; here they are one program);
* the math ORDER mirrors the container path exactly — dequant in f32, cast to
  the activation dtype, single full-K contraction, then ``(dot + bias) +
  delta`` — so greedy decode through the kernel is bit-identical to the
  XLA-container path (pinned by tools/quant_smoke.py and
  tests/test_quant_matmul.py).

Dispatch is probe-gated with the exact XLA container path as fallback
(``ops.attention._kernel_lowers`` discipline): ``DISTRL_QUANT_MATMUL`` =
``auto`` (kernel on TPU when the lowering probe passes; container path
elsewhere — the CPU tier-1 default, byte-identical to before this module),
``kernel`` (force; implies interpret off-TPU), ``interpret`` (Pallas
interpreter — CPU parity tests), ``xla`` (pin the container path).

Gradients: the kernel is wrapped in a ``jax.custom_vjp`` whose backward runs
``jax.vjp`` over the *reference* math, so the learner's QLoRA step (grads
through dequant into LoRA only — tests/test_quant.py) differentiates through
`linear`/`_proj` unchanged whichever path dispatched.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

#: trace-time dispatch record (the ops.paged.dispatch_choices idiom): keyed by
#: (bits, K, N, rank, dtype) → "kernel" | "xla"; bench reads it so a row
#: claiming the fused path can never have silently measured the container path
dispatch_choices: dict = {}

_probe_state: dict = {}

MODES = ("auto", "kernel", "interpret", "xla")


def quant_matmul_mode() -> str:
    """Resolved DISTRL_QUANT_MATMUL mode (validated; default "auto")."""
    mode = os.environ.get("DISTRL_QUANT_MATMUL", "auto")
    if mode not in MODES:
        raise ValueError(
            f"DISTRL_QUANT_MATMUL must be one of {MODES}, got {mode!r}"
        )
    return mode


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def _kernel_body(x_ref, q_ref, s_ref, *rest,
                 out_dtype, has_bias: bool, has_lora: bool,
                 lora_scale: float):
    """One (bm, bn) output tile: full-K dequant-matmul + optional bias +
    optional LoRA epilogue.

    The contraction is ONE ``jnp.dot`` over the whole K (not a K-block
    accumulation loop): decode-shape weights fit VMEM at int width, and a
    single dot keeps the per-element reduction order identical to the
    container path's einsum — the bit-identity contract."""
    rest = list(rest)
    bias_ref = rest.pop(0) if has_bias else None
    a_ref = rest.pop(0) if has_lora else None
    b_ref = rest.pop(0) if has_lora else None
    o_ref = rest.pop(0)

    x = x_ref[...]  # [bm, K]
    q3 = q_ref[...]  # [G, g, bn] int8/int4
    sc = s_ref[...]  # [G, 1, bn] f32
    gdim, g, bn = q3.shape
    # dequant exactly as the container path: q·scale in f32 (bf16-rounding
    # the scales would stack ~0.4% error), ONE cast to the activation dtype
    w = (q3.astype(jnp.float32) * sc).astype(x.dtype).reshape(gdim * g, bn)
    y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(out_dtype)
    if has_bias:
        y = y + bias_ref[...].astype(out_dtype)
    if has_lora:
        # LoRA epilogue, in lora_delta's exact dtype discipline: factors cast
        # to the activation dtype, delta never widens the residual stream
        a = a_ref[...].astype(x.dtype)  # [K, r]
        b = b_ref[...].astype(x.dtype)  # [r, bn]
        xa = jnp.dot(x, a, preferred_element_type=jnp.float32).astype(x.dtype)
        xab = jnp.dot(xa, b, preferred_element_type=jnp.float32).astype(x.dtype)
        y = y + (xab * jnp.asarray(lora_scale, x.dtype)).astype(out_dtype)
    o_ref[...] = y


def _kernel_call(x2, q, scale, bias, a, b, lora_scale: float,
                 *, interpret: bool):
    """Padded pallas_call over a [M, K] × container[K→G·g, N] matmul."""
    m, k = x2.shape
    gdim, g, n = q.shape
    out_dtype = x2.dtype

    bn = 128
    bm = 128 if m >= 128 else _round_up(m, 8)
    mp, np_ = _round_up(m, bm), _round_up(n, bn)
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    if np_ != n:
        # zero q/scale/bias/b columns dequantize to exact zeros — the padded
        # tail never contaminates real columns and is sliced off below
        q = jnp.pad(q, ((0, 0), (0, 0), (0, np_ - n)))
        scale = jnp.pad(scale, ((0, 0), (0, 0), (0, np_ - n)))
        if bias is not None:
            bias = jnp.pad(bias, ((0, np_ - n),))
        if b is not None:
            b = jnp.pad(b, ((0, 0), (0, np_ - n)))

    has_bias = bias is not None
    has_lora = a is not None
    grid = (mp // bm, np_ // bn)
    in_specs = [
        pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
        pl.BlockSpec((gdim, g, bn), lambda i, j: (0, 0, j)),
        pl.BlockSpec((gdim, 1, bn), lambda i, j: (0, 0, j)),
    ]
    operands = [x2, q, scale]
    if has_bias:
        in_specs.append(pl.BlockSpec((1, bn), lambda i, j: (0, j)))
        operands.append(bias.reshape(1, np_))
    if has_lora:
        r = a.shape[-1]
        in_specs.append(pl.BlockSpec((k, r), lambda i, j: (0, 0)))
        in_specs.append(pl.BlockSpec((r, bn), lambda i, j: (0, j)))
        operands.extend([a, b])

    out = pl.pallas_call(
        functools.partial(
            _kernel_body, out_dtype=out_dtype, has_bias=has_bias,
            has_lora=has_lora, lora_scale=lora_scale,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), out_dtype),
        interpret=interpret,
    )(*operands)
    return out[:m, :n]


def _reference(x2, q, scale, bias, a, b, lora_scale):
    """The exact XLA-container math (ops/linear.py + lora_delta), flattened
    to the kernel's argument list — the fallback path AND the custom-VJP
    backward's primal."""
    gdim, g, n = q.shape
    w = (q.astype(jnp.float32) * scale).astype(x2.dtype).reshape(gdim * g, n)
    y = jnp.einsum("mi,io->mo", x2, w)
    if bias is not None:
        y = y + bias
    if a is not None:
        ac = a.astype(x2.dtype)
        bc = b.astype(x2.dtype)
        y = y + (x2 @ ac @ bc) * jnp.asarray(lora_scale, x2.dtype)
    return y


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _quant_matmul_p(x2, q, scale, bias, a, b, lora_scale, interpret):
    return _kernel_call(x2, q, scale, bias, a, b, lora_scale,
                        interpret=interpret)


def _qmm_fwd(x2, q, scale, bias, a, b, lora_scale, interpret):
    out = _kernel_call(x2, q, scale, bias, a, b, lora_scale,
                       interpret=interpret)
    return out, (x2, q, scale, bias, a, b)


def _qmm_bwd(lora_scale, interpret, res, g_out):
    # backward through the REFERENCE math: standard XLA matmul grads (dx,
    # dbias, dA, dB; int payloads get float0) — QLoRA trains LoRA only, so
    # a Pallas backward kernel would buy nothing the forward didn't
    del interpret
    x2, q, scale, bias, a, b = res
    _, vjp = jax.vjp(
        lambda *args: _reference(*args, lora_scale), x2, q, scale, bias, a, b
    )
    return vjp(g_out)


_quant_matmul_p.defvjp(_qmm_fwd, _qmm_bwd)


def _kernel_lowers(k: int, n: int, gdim: int, g: int, bits: int, rank: int,
                   dtype) -> bool:
    """Probe-compile the kernel at this (K, N, groups, bits, rank) config —
    Mosaic block-rule/int-width rejections fire at COMPILE time, past any
    try/except around a traced call inside a larger jit (the round-3 paged
    lesson, ops/paged_int8.py)."""
    key = (k, n, gdim, g, bits, rank, jnp.dtype(dtype).name)
    if key not in _probe_state:
        try:
            qdt = jnp.int4 if bits == 4 else jnp.int8
            x = jnp.zeros((8, k), dtype)
            q = jnp.zeros((gdim, g, n), qdt)
            s = jnp.zeros((gdim, 1, n), jnp.float32)
            a = jnp.zeros((k, rank), dtype) if rank else None
            b = jnp.zeros((rank, n), dtype) if rank else None
            jax.block_until_ready(
                _kernel_call(x, q, s, None, a, b, 1.0, interpret=False)
            )
            _probe_state[key] = True
        except Exception as e:  # noqa: BLE001 — fall back, loudly, once
            _probe_state[key] = False
            logger.warning(
                "quant_matmul kernel failed its lowering probe for %s (%s); "
                "using the XLA container path", key, e,
            )
    return _probe_state[key]


def quant_matmul_dispatch(q_shape, bits: int, rank: int, k: int,
                          dtype) -> tuple[bool, bool]:
    """(use_kernel, interpret) for this call, per DISTRL_QUANT_MATMUL.

    "auto" engages the kernel only on TPU and only when the probe compiles
    (CPU/tier-1 keeps the container path byte-identically); "kernel" forces
    it (interpreted off-TPU — the CI/e2e drill); "interpret" forces the
    Pallas interpreter everywhere; "xla" pins the container path."""
    mode = quant_matmul_mode()
    if mode == "xla":
        return False, False
    on_tpu = jax.default_backend() == "tpu"
    if mode == "interpret":
        return True, True
    if mode == "kernel":
        return True, not on_tpu
    gdim, g, n = q_shape
    return (on_tpu and _kernel_lowers(k, n, gdim, g, bits, rank, dtype)), False


def quant_matmul(
    x: jax.Array,  # [..., K]
    w: dict,  # {"q": [G, g, N] int8/int4, "scale": [G, 1, N] f32}
    bias: jax.Array | None = None,
    lora_a: jax.Array | None = None,  # [K, r]
    lora_b: jax.Array | None = None,  # [r, N]
    lora_scale: float = 1.0,
    *,
    interpret: bool = False,
) -> jax.Array:
    """Fused dequant-matmul (+ bias, + LoRA epilogue) through the Pallas
    kernel. Callers go through ``linear()``/``_proj`` which decide the
    kernel-vs-container dispatch; this entry point always runs the kernel
    (``interpret`` selects the Pallas interpreter for CPU parity)."""
    q, scale = w["q"], w["scale"]
    if q.ndim != 3:
        raise ValueError(
            f"quant_matmul takes per-layer containers [G, g, N], got "
            f"q.shape={q.shape} (stacked trees are sliced per layer by the "
            "transformer's unrolled loop)"
        )
    lead = x.shape[:-1]
    k = x.shape[-1]
    if q.shape[0] * q.shape[1] != k:
        raise ValueError(
            f"container input dim {q.shape[0]}x{q.shape[1]} != x's {k}"
        )
    x2 = x.reshape(-1, k)
    out = _quant_matmul_p(
        x2, q, scale, bias, lora_a, lora_b,
        float(lora_scale), interpret,
    )
    return out.reshape(*lead, q.shape[-1])
