"""Ring attention: causal GQA attention with the sequence sharded over "sp".

The long-context learner path the reference cannot express (SURVEY §2c/§5:
max sequence is hard-fixed at 1,550 tokens — distributed_actor.py:25; scaling
CoT to 4k+ needs sequence parallelism). Design:

* q/k/v are sequence-sharded over the mesh's ``sp`` axis (shard_map); each
  device owns one contiguous chunk of the sequence.
* KV chunks rotate around the ring with ``lax.ppermute`` (ICI
  neighbor-to-neighbor — the cheapest collective there is) while each device
  folds every chunk into an online-softmax accumulator (running max ``m``,
  normalizer ``l``, weighted sum ``o``) — the flash-attention recurrence, so
  no device ever materializes more than [B, c, H, c] logits for chunk c = S/sp.
* causality and key padding are applied per chunk from GLOBAL positions
  (chunk index × chunk length + local offset), so the result matches the
  single-device ``causal_padding_mask`` formulation exactly.
* grouped-query heads contract directly against the K kv heads (same trick
  as ops/attention.py — no repeat_kv materialization).

Gradients flow through shard_map/ppermute, so the same function serves the
learner's forward AND backward; `jax.checkpoint` composes around it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distrl_llm_tpu.ops.attention import NEG_INF

# jax.shard_map is the promoted (>= 0.6) spelling; older jax ships it in
# experimental only — same drift class as pltpu.CompilerParams (CI triage)
try:
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map as _shard_map


def _chunk_logits(q, k, scale):
    """Grouped-query logits: q [B,c,K,G,D] × k [B,s,K,D] → [B,K,G,c,s] f32."""
    return jnp.einsum("bqkgd,bskd->bkgqs", q, k, preferred_element_type=jnp.float32) * scale


def _ring_local(q, k, v, kv_valid, *, axis_name: str, sp: int, scale: float,
                varying_axes: tuple[str, ...]):
    """Per-shard body. q/k/v: [B, c, H|K, D] local chunks; kv_valid: [B, c]."""
    b, c, h, d = q.shape
    kh = k.shape[2]
    g = h // kh
    qg = q.astype(jnp.float32).reshape(b, c, kh, g, d)
    my = jax.lax.axis_index(axis_name)
    q_pos = my * c + jax.lax.broadcasted_iota(jnp.int32, (c, 1), 0)  # [c, 1]

    # online-softmax accumulators — pcast marks the constant inits as
    # varying over the same mesh axes as the sharded inputs so the fori_loop
    # carry type matches the updated values under shard_map's varying-axis
    # typing
    m = jnp.full((b, kh, g, c), NEG_INF, jnp.float32)
    l = jnp.zeros((b, kh, g, c), jnp.float32)
    o = jnp.zeros((b, kh, g, c, d), jnp.float32)
    # (older jax has no pcast — and no varying-axis typing to satisfy, so
    # skipping the cast there is exactly equivalent)
    if hasattr(jax.lax, "pcast"):
        m, l, o = jax.lax.pcast((m, l, o), varying_axes, to="varying")

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def fold(j, m, l, o, k, v, kv_valid):
        """Fold the chunk currently held (originally from device my − j) into
        the online-softmax accumulators."""
        src = (my - j) % sp
        kv_pos = src * c + jax.lax.broadcasted_iota(jnp.int32, (1, c), 1)  # [1, c]
        allowed = (kv_pos <= q_pos)[None, None, None]  # [1,1,1,c,c] causal
        allowed = allowed & kv_valid[:, None, None, None, :].astype(bool)
        s_blk = _chunk_logits(qg, k.astype(jnp.float32), scale)  # [B,K,G,c,c]
        s_blk = jnp.where(allowed, s_blk, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s_blk, axis=-1))
        # guard exp(NEG_INF - NEG_INF) for all-masked rows
        alpha = jnp.exp(jnp.clip(m - m_new, a_min=-80.0, a_max=0.0))
        p = jnp.exp(jnp.clip(s_blk - m_new[..., None], a_min=-80.0, a_max=0.0))
        p = jnp.where(allowed, p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bkgqs,bskd->bkgqd", p, v.astype(jnp.float32)
        )
        return m_new, l, o

    def step(j, carry):
        m, l, o, k, v, kv_valid = carry
        m, l, o = fold(j, m, l, o, k, v, kv_valid)
        k, v, kv_valid = jax.lax.ppermute((k, v, kv_valid), axis_name, perm)
        return m, l, o, k, v, kv_valid

    # rotate sp−1 times; the last chunk is folded outside the loop so the
    # final (discarded) ppermute never happens
    m, l, o, k, v, kv_valid = jax.lax.fori_loop(
        0, sp - 1, step, (m, l, o, k, v, kv_valid)
    )
    m, l, o = fold(sp - 1, m, l, o, k, v, kv_valid)
    out = jnp.where(l[..., None] > 0, o / jnp.maximum(l, 1e-30)[..., None], 0.0)
    # [B,K,G,c,D] → [B,c,H,D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, c, h, d).astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] global
    k: jax.Array,  # [B, S, K, D]
    v: jax.Array,  # [B, S, K, D]
    key_valid: jax.Array,  # [B, S] 1 = real token
    *,
    mesh: Mesh,
    scale: float | None = None,
    axis_name: str = "sp",
    batch_axis: str | None = "dp",
) -> jax.Array:
    """Causal self-attention with sequence parallelism over ``axis_name``.

    Semantics match ``attention_reference(q, k, v,
    causal_padding_mask(key_valid, S))`` up to f32 accumulation order; S must
    divide evenly by the sp axis size.

    The batch dim is additionally sharded over ``batch_axis`` when it divides
    evenly (otherwise replicated — correct but redundant across that axis).
    Heads stay unsharded: the learner mesh this serves uses dp×sp(×fsdp for
    params); combine tp with ring only by threading a head spec here first.
    """
    sp = mesh.shape[axis_name]
    s = q.shape[1]
    if s % sp != 0:
        raise ValueError(f"sequence {s} not divisible by sp={sp}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b_ax = batch_axis
    if b_ax is not None and (
        b_ax not in mesh.shape or q.shape[0] % mesh.shape[b_ax] != 0
    ):
        b_ax = None
    varying = (axis_name,) if b_ax is None else (b_ax, axis_name)
    body = partial(
        _ring_local, axis_name=axis_name, sp=sp, scale=scale,
        varying_axes=varying,
    )
    seq_spec = P(b_ax, axis_name, None, None)
    return _shard_map(
        body,
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec, P(b_ax, axis_name)),
        out_specs=seq_spec,
    )(q, k, v, key_valid)
