"""Splash attention (Pallas) for GQA training forwards — no repeat_kv.

VERDICT r1 flagged the flash path's GQA handling: jaxlib's flash kernel
demands equal head counts, so K/V are ``jnp.repeat``-ed to full heads — the
exact KV traffic multiplication (7× for Qwen2.5-0.5B) the decode path avoids.
The splash kernel is natively multi-query: built per KV head group
(``make_splash_mqa_single_device``) and vmapped over KV heads and batch, K/V
move through the kernel ONCE at their true head count.

Causality + right-padding come from a CausalMask plus SegmentIds (padding
tokens get segment 0, real tokens 1 — cross-segment attention is masked).
``interpret=True`` runs the same kernel under the Pallas interpreter so CPU
CI tests true parity with the XLA reference (tests/test_splash.py).

Selected via ``attn_impl="splash"`` (training/uncached forwards only; decode
uses the paged/cached paths).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.cache
def _mods():
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as kernel,
        splash_attention_mask as mask_lib,
    )

    return kernel, mask_lib


@functools.cache
def _make_kernel(groups: int, seq: int, block: int, interpret: bool):
    kernel, mask_lib = _mods()
    mask = mask_lib.MultiHeadMask(
        [mask_lib.CausalMask((seq, seq)) for _ in range(groups)]
    )
    block_sizes = kernel.BlockSizes(
        block_q=min(block, seq),
        block_kv=min(block, seq),
        block_kv_compute=min(block, seq),
        block_q_dkv=min(block, seq),
        block_kv_dkv=min(block, seq),
        block_kv_dkv_compute=min(block, seq),
        block_q_dq=min(block, seq),
        block_kv_dq=min(block, seq),
    )
    return kernel.make_splash_mqa_single_device(
        mask, block_sizes=block_sizes, interpret=interpret
    )


def splash_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, K, D]
    v: jax.Array,  # [B, S, K, D]
    key_valid: jax.Array | None,  # [B, S] 1 = real token
    scale: float | None = None,
    block: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Causal GQA self-attention via the splash kernel. Differentiable
    (custom-VJP kernels). Sequence must be a multiple of the kernel's lane
    width; callers' fixed shapes are padded here if needed.

    ``interpret=True`` runs the Pallas interpreter (tests on CPU — orders of
    magnitude slower than the XLA reference; production non-TPU callers
    should fall back via ``attention(..., impl="splash")`` instead)."""
    kernel, _ = _mods()
    b, s, h, d = q.shape
    kh = k.shape[2]
    if h % kh:
        raise ValueError(f"q heads {h} not divisible by kv heads {kh}")
    g = h // kh
    if scale is None:
        scale = d**-0.5

    pad = (-s) % 128  # splash lane granularity
    if pad:
        q = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if key_valid is not None:
            key_valid = jnp.pad(key_valid, ((0, 0), (0, pad)))
    sp = s + pad

    if key_valid is None:
        key_valid = jnp.ones((b, sp), jnp.int32)
    seg = kernel.SegmentIds(
        q=key_valid.astype(jnp.int32), kv=key_valid.astype(jnp.int32)
    )

    splash = _make_kernel(g, sp, block, interpret)
    # [B, S, H, D] → per-KV-head groups [B, K, G, S, D]; K/V [B, K, S, D]
    qg = (q * scale).transpose(0, 2, 1, 3).reshape(b, kh, g, sp, d)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    # vmap over KV heads (shared segment ids), then over batch
    per_head = jax.vmap(splash, in_axes=(0, 0, 0, None))
    per_batch = jax.vmap(per_head, in_axes=(0, 0, 0, 0))
    out = per_batch(qg, kt, vt, seg)  # [B, K, G, S, D]
    out = out.reshape(b, h, sp, d).transpose(0, 2, 1, 3)
    if pad:
        out = out[:, :s]
    return out.astype(q.dtype)
