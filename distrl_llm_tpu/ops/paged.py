"""Paged KV cache ops: page tables, token writes, and ragged paged attention.

The N1 core the reference delegates to vLLM's PagedAttention
(requirements.txt:6; engine entered via ``policy.fast_generate``,
distributed_actor.py:148–150). TPU-native design:

* **Pages** are [num_kv_heads, total_pages, page_size, head_dim] arrays per
  layer; a row's sequence lives at the pages listed in its ``page_indices``
  row, valid up to ``lengths[row]`` tokens. Prompts are PACKED (position 0 is
  the first real token — no left padding inside the cache), so attention
  bandwidth is proportional to each row's true length, not the cache
  capacity: the decode kernel only reads [0, length) — vLLM's ragged read,
  where the dense cache reads all of Smax every step for every row.
* **Static page tables.** vLLM's C++ block allocator exists to multiplex an
  unknown online request stream; an RL rollout round is a FIXED batch of
  B·n candidates with known capacity, so the table is a host-computed
  constant per round (row-major identity layout today; the indirection layer
  is what lets prompt-prefix sharing land without touching the kernel).
* **Kernel**: jaxlib's Pallas TPU ``paged_attention`` (Mosaic) on TPU; a
  jnp reference with identical semantics elsewhere and for parity tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from distrl_llm_tpu.ops.attention import NEG_INF

DEFAULT_PAGE_SIZE = 128


def pages_per_seq(max_len: int, page_size: int = DEFAULT_PAGE_SIZE) -> int:
    return -(-max_len // page_size)


def make_page_table(
    n_rows: int, max_len: int, page_size: int = DEFAULT_PAGE_SIZE
) -> np.ndarray:
    """Row-major identity page table: row r owns pages [r·pps, (r+1)·pps).

    int32 [n_rows, pages_per_seq]. Total pages = n_rows · pages_per_seq."""
    pps = pages_per_seq(max_len, page_size)
    return (
        np.arange(n_rows, dtype=np.int32)[:, None] * pps
        + np.arange(pps, dtype=np.int32)[None, :]
    )


def init_paged_kv_cache(
    cfg, n_rows: int, max_len: int, page_size: int = DEFAULT_PAGE_SIZE,
    dtype=jnp.bfloat16,
):
    """Per-layer page arrays for ``n_rows`` sequences of capacity ``max_len``.

    Layout [K, total_pages, page_size, hd] matches the Pallas kernel's
    contract (paged_attention_kernel.py)."""
    pps = pages_per_seq(max_len, page_size)
    shape = (cfg.num_kv_heads, n_rows * pps, page_size, cfg.head_dim)
    return {
        "k": tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
        "v": tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
    }


def write_prompt_to_pages(
    pages: jax.Array,  # [K, total_pages, ps, hd]
    prompt_kv: jax.Array,  # [B, P, K, hd] packed (row position 0 = first token)
    page_indices: jax.Array,  # [B, pps_total]
    page_size: int,
) -> jax.Array:
    """Write every row's packed prompt KV into its leading pages.

    P must be a multiple of page_size (callers pad; positions beyond a row's
    real length hold garbage that ``lengths`` masking never reads)."""
    b, p, kh, hd = prompt_kv.shape
    assert p % page_size == 0, (p, page_size)
    n_prompt_pages = p // page_size
    # [B, P, K, hd] → [K, B·n_prompt_pages, ps, hd]
    tiles = (
        prompt_kv.reshape(b, n_prompt_pages, page_size, kh, hd)
        .transpose(3, 0, 1, 2, 4)
        .reshape(kh, b * n_prompt_pages, page_size, hd)
    )
    dest = page_indices[:, :n_prompt_pages].reshape(-1)  # [B·n_prompt_pages]
    return pages.at[:, dest].set(tiles.astype(pages.dtype))


def write_token_to_pages(
    pages: jax.Array,  # [K, total_pages, ps, hd]
    new_kv: jax.Array,  # [B, K, hd] — one token per row
    lengths: jax.Array,  # [B] current token counts (write position)
    page_indices: jax.Array,  # [B, pps]
    page_size: int,
) -> jax.Array:
    """Scatter one decoded token's KV into each row's current page slot."""
    b = new_kv.shape[0]
    rows = jnp.arange(b)
    page = page_indices[rows, lengths // page_size]  # [B]
    slot = lengths % page_size  # [B]
    return pages.at[:, page, slot].set(
        new_kv.transpose(1, 0, 2).astype(pages.dtype)
    )


def paged_attention_reference(
    q: jax.Array,  # [B, H, hd] — single decode query per row
    k_pages: jax.Array,  # [K, total_pages, ps, hd]
    v_pages: jax.Array,  # [K, total_pages, ps, hd]
    lengths: jax.Array,  # [B] valid token counts (incl. current position)
    page_indices: jax.Array,  # [B, pps]
    scale: float | None = None,
) -> jax.Array:
    """jnp semantics-reference for the Pallas kernel: gather each row's pages
    and run masked GQA attention over its valid prefix."""
    b, h, hd = q.shape
    kh = k_pages.shape[0]
    g = h // kh
    ps = k_pages.shape[2]
    if scale is None:
        scale = hd**-0.5
    # gather [K, B, pps, ps, hd] → [B, K, S, hd]
    k = k_pages[:, page_indices].transpose(1, 0, 2, 3, 4)
    v = v_pages[:, page_indices].transpose(1, 0, 2, 3, 4)
    s = k.shape[2] * ps
    k = k.reshape(b, kh, s, hd)
    v = v.reshape(b, kh, s, hd)
    qg = q.reshape(b, kh, g, hd).astype(jnp.float32)
    logits = jnp.einsum("bkgd,bksd->bkgs", qg, k.astype(jnp.float32)) * scale
    valid = jnp.arange(s)[None, :] < lengths[:, None]  # [B, S]
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgs,bksd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, h, hd).astype(q.dtype)


_kernel_fail_warned = False


def paged_attention_op(
    q: jax.Array,  # [B, H, hd]
    k_pages: jax.Array,
    v_pages: jax.Array,
    lengths: jax.Array,
    page_indices: jax.Array,
    *,
    impl: str = "auto",
    pages_per_compute_block: int = 4,
) -> jax.Array:
    """Dispatch: Pallas TPU kernel when available, jnp reference otherwise.

    ``impl``: "auto" (kernel on TPU backends, reference elsewhere),
    "kernel", or "reference"."""
    use_kernel = impl == "kernel" or (
        impl == "auto" and jax.default_backend() == "tpu"
    )
    if use_kernel:
        try:
            from jax.experimental.pallas.ops.tpu.paged_attention import (
                paged_attention,
            )

            # the kernel computes raw q·k (no internal scaling) and requires
            # pages_per_sequence % pages_per_compute_block == 0
            pps = page_indices.shape[1]
            blocks = max(
                (d for d in range(1, min(pages_per_compute_block, pps) + 1)
                 if pps % d == 0),
                default=1,
            )
            scaled_q = q * (q.shape[-1] ** -0.5)
            return paged_attention(
                scaled_q, k_pages, v_pages, lengths.astype(jnp.int32),
                page_indices, pages_per_compute_block=blocks,
            ).astype(q.dtype)
        except Exception as e:  # noqa: BLE001 — fall back with one warning
            if impl == "kernel":
                raise
            global _kernel_fail_warned
            if not _kernel_fail_warned:
                _kernel_fail_warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "paged_attention kernel unavailable (%s); using reference",
                    e,
                )
    return paged_attention_reference(q, k_pages, v_pages, lengths, page_indices)
