"""Paged KV cache ops: page tables, token writes, and ragged paged attention.

The N1 core the reference delegates to vLLM's PagedAttention
(requirements.txt:6; engine entered via ``policy.fast_generate``,
distributed_actor.py:148–150). TPU-native design:

* **Pages** are [num_kv_heads, total_pages, page_size, head_dim] arrays per
  layer; a row's sequence lives at the pages listed in its ``page_indices``
  row, valid up to ``lengths[row]`` tokens. Prompts are PACKED (position 0 is
  the first real token — no left padding inside the cache), so attention
  bandwidth is proportional to each row's true length, not the cache
  capacity: the decode kernel only reads [0, length) — vLLM's ragged read,
  where the dense cache reads all of Smax every step for every row.
* **Shape-static, host-authored page tables.** vLLM's C++ block allocator
  multiplexes an unknown online request stream; an RL rollout round is a
  FIXED batch of B·n candidates, so the tables are host-computed int32
  arrays of STATIC shape whose CONTENT changes (engine/page_pool.py: the
  free-list allocator behind ``--actor_gpu_usage`` grants pages as
  sequences grow and rewrites rows on admission/preemption; wave mode uses
  a per-round constant layout). The indirection layer is also what lets
  prompt-prefix sharing land without touching the kernel.
* **Kernel**: jaxlib's Pallas TPU ``paged_attention`` (Mosaic) on TPU — via
  the compact-scales launch (ops/paged_int8.py) for int8 pages; a jnp
  reference with identical semantics elsewhere and for parity tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from distrl_llm_tpu.ops.attention import NEG_INF

DEFAULT_PAGE_SIZE = 128


def _quant_utils():
    from jax.experimental.pallas.ops.tpu.paged_attention import (
        quantization_utils,
    )

    return quantization_utils


def is_quantized_pages(pages) -> bool:
    """True for the kernel's QuantizedTensor page container (int8 weight +
    per-token absmax scales)."""
    return hasattr(pages, "weight") and hasattr(pages, "scales")


def quantize_pages(pages: jax.Array):
    """float pages [K, P, ps, hd] → QuantizedTensor (int8 + f32 scales
    [K, P, ps, 1]). Halves the cache's resident HBM footprint.

    Decode-speed note: jaxlib's public ``paged_attention`` wrapper broadcasts
    these scales to head_dim before its pallas_call (a full-cache f32 temp
    per step, which would negate the bandwidth win); the TPU kernel path
    here uses the COMPACT-scales launch instead (ops/paged_int8.py — same
    jaxlib kernel, scales shipped [ps, 1], ~1 + 4/head_dim bytes/element),
    so int8 KV buys both capacity AND read bandwidth."""
    return _quant_utils().quantize_to_int8(pages)


def init_quantized_pages(shape: tuple[int, int, int, int]):
    """Zero-initialized QuantizedTensor pages for ``shape``
    [K, total_pages, ps, hd] — the single owner of the quantized-page layout
    contract (int8 weight + f32 per-token scales [..., 1])."""
    qu = _quant_utils()
    return qu.QuantizedTensor(
        weight=jnp.zeros(shape, jnp.int8),
        scales=jnp.zeros(shape[:3] + (1,), jnp.float32),
    )


def dequantize_pages(pages, dtype=jnp.float32) -> jax.Array:
    if not is_quantized_pages(pages):
        return pages.astype(dtype)
    return _quant_utils().from_int8(pages.weight, pages.scales, dtype=dtype)


def pages_per_seq(max_len: int, page_size: int = DEFAULT_PAGE_SIZE) -> int:
    return -(-max_len // page_size)


def make_page_table(
    n_rows: int, max_len: int, page_size: int = DEFAULT_PAGE_SIZE
) -> np.ndarray:
    """Row-major identity page table: row r owns pages [r·pps, (r+1)·pps).

    int32 [n_rows, pages_per_seq]. Total pages = n_rows · pages_per_seq."""
    pps = pages_per_seq(max_len, page_size)
    return (
        np.arange(n_rows, dtype=np.int32)[:, None] * pps
        + np.arange(pps, dtype=np.int32)[None, :]
    )


def init_paged_kv_cache(
    cfg, n_rows: int, max_len: int, page_size: int = DEFAULT_PAGE_SIZE,
    dtype=jnp.bfloat16,
):
    """Per-layer page arrays for ``n_rows`` sequences of capacity ``max_len``.

    Layout [K, total_pages, page_size, hd] matches the Pallas kernel's
    contract (paged_attention_kernel.py)."""
    pps = pages_per_seq(max_len, page_size)
    shape = (cfg.num_kv_heads, n_rows * pps, page_size, cfg.head_dim)
    return {
        "k": tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
        "v": tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
    }


def write_prompt_to_pages(
    pages,  # [K, total_pages, ps, hd] array, or QuantizedTensor
    prompt_kv: jax.Array,  # [B, P, K, hd] packed (row position 0 = first token)
    page_indices: jax.Array,  # [B, pps_total]
    page_size: int,
):
    """Write every row's packed prompt KV into its leading pages.

    P must be a multiple of page_size (callers pad; positions beyond a row's
    real length hold garbage that ``lengths`` masking never reads)."""
    b, p, kh, hd = prompt_kv.shape
    assert p % page_size == 0, (p, page_size)
    n_prompt_pages = p // page_size
    # [B, P, K, hd] → [K, B·n_prompt_pages, ps, hd]
    tiles = (
        prompt_kv.reshape(b, n_prompt_pages, page_size, kh, hd)
        .transpose(3, 0, 1, 2, 4)
        .reshape(kh, b * n_prompt_pages, page_size, hd)
    )
    dest = page_indices[:, :n_prompt_pages].reshape(-1)  # [B·n_prompt_pages]
    if is_quantized_pages(pages):
        qu = _quant_utils()
        scales = qu.get_quantization_scales(tiles)  # [K, tiles, ps, 1]
        weight = pages.weight.at[:, dest].set(qu.to_int8(tiles, scales))
        return type(pages)(
            weight=weight,
            scales=pages.scales.at[:, dest].set(scales.astype(pages.scales.dtype)),
        )
    return pages.at[:, dest].set(tiles.astype(pages.dtype))


def write_token_to_pages(
    pages,  # [K, total_pages, ps, hd] array, or QuantizedTensor
    new_kv: jax.Array,  # [B, K, hd] — one token per row
    lengths: jax.Array,  # [B] current token counts (write position)
    page_indices: jax.Array,  # [B, pps]
    page_size: int,
    valid: jax.Array | None = None,  # [B] bool; False rows write nothing
):
    """Scatter one decoded token's KV into each row's current page slot.

    With ``valid``, rows marked False are DROPPED (out-of-range page index +
    ``mode="drop"``) instead of written — the continuation-prefill path uses
    this so padding positions never touch pages the row doesn't own."""
    b = new_kv.shape[0]
    rows = jnp.arange(b)
    page = page_indices[rows, lengths // page_size]  # [B]
    slot = lengths % page_size  # [B]
    raw = pages.weight if is_quantized_pages(pages) else pages
    if valid is not None:
        page = jnp.where(valid, page, raw.shape[1])  # OOB → dropped
    tok = new_kv.transpose(1, 0, 2)  # [K, B, hd]
    if is_quantized_pages(pages):
        qu = _quant_utils()
        scales = qu.get_quantization_scales(tok)  # [K, B, 1]
        weight = pages.weight.at[:, page, slot].set(
            qu.to_int8(tok, scales), mode="drop"
        )
        return type(pages)(
            weight=weight,
            scales=pages.scales.at[:, page, slot].set(
                scales.astype(pages.scales.dtype), mode="drop"
            ),
        )
    return pages.at[:, page, slot].set(tok.astype(pages.dtype), mode="drop")


def write_tokens_to_pages(
    pages,  # [K, total_pages, ps, hd] array, or QuantizedTensor
    new_kv: jax.Array,  # [B, D, K, hd] — D tokens per row
    lengths: jax.Array,  # [B] current token counts (first write position)
    page_indices: jax.Array,  # [B, pps]
    page_size: int,
    valid: jax.Array | None = None,  # [B, D] bool per-token validity
):
    """Scatter D consecutive tokens' KV per row (speculative-decode verify
    writes the whole draft block at once; D is small and static, so the loop
    unrolls inside the jitted step)."""
    d = new_kv.shape[1]
    for i in range(d):
        pages = write_token_to_pages(
            pages, new_kv[:, i], lengths + i, page_indices, page_size,
            valid=valid[:, i] if valid is not None else None,
        )
    return pages


def gather_pages_dense(pages, page_indices: jax.Array,
                       dtype=jnp.float32) -> jax.Array:
    """Gather each row's pages into a dense position-ordered context
    [B, width·ps, K, hd] (page-table column t covers positions
    [t·ps, (t+1)·ps), so the concatenation is position order). Quantized
    pools dequantize AFTER the gather — only the rows' own pages.

    ``dtype`` defaults to f32 (the chunked-attention accumulator contract);
    the warm radix-prefill path passes the COMPUTE dtype so the gathered
    context is bit-identical to the in-flight k/v the packed cold prefill
    attended over (page writes are exact ``astype`` round-trips when the
    pool dtype holds the compute dtype losslessly)."""
    if is_quantized_pages(pages):
        w = pages.weight[:, page_indices]
        s_ = pages.scales[:, page_indices]
        dense = _quant_utils().from_int8(w, s_, dtype=dtype)
    else:
        dense = pages[:, page_indices].astype(dtype)
    # [K, B, width, ps, hd] → [B, width·ps, K, hd]
    kh, b, width, ps, hd = dense.shape
    return dense.transpose(1, 2, 3, 0, 4).reshape(b, width * ps, kh, hd)


def chunked_context_attention(
    q: jax.Array,  # [B, S, H, hd] — S continuation queries per row
    ctx_k: jax.Array,  # [B, Sk, K, hd] dense-gathered context (f32)
    ctx_v: jax.Array,
    lengths: jax.Array,  # [B] resident tokens BEFORE the continuation block
    q_valid: jax.Array,  # [B, S] bool/int — which continuation tokens are real
) -> jax.Array:
    """Attention for chunked (continuation) prefill over a paged cache: query
    i at global position lengths+i attends context positions j <= lengths+i.
    The context already contains the continuation block's own KV (written to
    pages before the gather), so this is exact causality — vLLM's chunked
    prefill, dense-gather edition (ops are plain einsums; XLA fuses)."""
    b, s, h, hd = q.shape
    kh = ctx_k.shape[2]
    g = h // kh
    sk = ctx_k.shape[1]
    scale = hd**-0.5
    qg = q.reshape(b, s, kh, g, hd).astype(jnp.float32)
    logits = jnp.einsum("bskgd,bjkd->bkgsj", qg, ctx_k) * scale  # [B,K,g,S,Sk]
    jpos = jnp.arange(sk)[None, None, :]  # [1, 1, Sk]
    qpos = lengths[:, None, None] + jnp.arange(s)[None, :, None]  # [B, S, 1]
    causal = jpos <= qpos  # [B, S, Sk]
    logits = jnp.where(causal[:, None, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgsj,bjkd->bskgd", probs, ctx_v)  # [B, S, K, g, hd]
    # invalid (padding) queries produce garbage rows — zero them so NaNs
    # can't propagate into downstream reductions
    out = jnp.where(q_valid[:, :, None, None, None] > 0, out, 0.0)
    return out.reshape(b, s, h, hd).astype(q.dtype)


def paged_attention_reference(
    q: jax.Array,  # [B, H, hd] — single decode query per row
    k_pages,  # [K, total_pages, ps, hd] array, or QuantizedTensor
    v_pages,
    lengths: jax.Array,  # [B] valid token counts (incl. current position)
    page_indices: jax.Array,  # [B, pps]
    scale: float | None = None,
) -> jax.Array:
    """jnp semantics-reference for the Pallas kernel: gather each row's pages
    and run masked GQA attention over its valid prefix (quantized pools
    dequantize AFTER the gather — only the rows' own pages)."""

    def gather(pages):
        if is_quantized_pages(pages):
            w = pages.weight[:, page_indices]
            s_ = pages.scales[:, page_indices]
            return _quant_utils().from_int8(w, s_, dtype=jnp.float32)
        return pages[:, page_indices].astype(jnp.float32)

    raw_k = k_pages.weight if is_quantized_pages(k_pages) else k_pages
    b, h, hd = q.shape
    kh = raw_k.shape[0]
    g = h // kh
    ps = raw_k.shape[2]
    if scale is None:
        scale = hd**-0.5
    # gather [K, B, pps, ps, hd] → [B, K, S, hd]
    k = gather(k_pages).transpose(1, 0, 2, 3, 4)
    v = gather(v_pages).transpose(1, 0, 2, 3, 4)
    s = k.shape[2] * ps
    k = k.reshape(b, kh, s, hd)
    v = v.reshape(b, kh, s, hd)
    qg = q.reshape(b, kh, g, hd).astype(jnp.float32)
    logits = jnp.einsum("bkgd,bksd->bkgs", qg, k.astype(jnp.float32)) * scale
    valid = jnp.arange(s)[None, :] < lengths[:, None]  # [B, S]
    logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bkgs,bksd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, h, hd).astype(q.dtype)


_kernel_fail_warned = False
_fixed_launch_state: dict = {}

#: kernel default for the blocked launch's page-axis collapse — callers
#: passing 0 get this (kept here so plan resolution, bench records, and the
#: analytic grid-step model all agree on what "default" means)
DEFAULT_PAGES_PER_BLOCK = 8


def paged_grid_steps(
    impl: str, *, batch: int, num_kv_heads: int, pps: int,
    pages_per_block: int = 0,
) -> int:
    """Analytic Pallas grid-step count of ONE paged-attention call (one
    layer, one decode step) for ``impl``. This is the denominator of the
    round-5 overhead model (BASELINE.md): decode at the benched geometry is
    bound by grid steps × Mosaic's ~1 µs/grid-step floor, not by bandwidth,
    so every engine/bench artifact records this count (ops/paged_grid_steps
    counter, bench ``grid_steps_estimate``) to make the regime visible.

    Counts per impl: "native" runs a (B, K, pps) grid; "native_folded"
    folds kv heads into the block — (B, pps); "native_blocked" additionally
    collapses the page axis — (B, ceil(pps / pages_per_block));
    "native_verify" is the FUSED draft-block verify: the whole (d+1)-query
    speculative verify step in ONE blocked sweep — same (B,
    ceil(pps / pages_per_block)) count as "native_blocked", where the
    unrolled verify paid that count (d+1) TIMES per step; the jaxlib
    kernels ("fixed"/"jaxlib"/"kernel") walk pages with manual DMA inside a
    (1, B, K) grid; the jnp reference has no Pallas grid (0)."""
    base = impl.split("!")[0]  # strip the "!transient-probe" honesty marker
    if base == "native":
        return batch * num_kv_heads * pps
    if base == "native_folded":
        return batch * pps
    if base in ("native_blocked", "native_verify"):
        ppb = max(1, min(pages_per_block or DEFAULT_PAGES_PER_BLOCK, pps))
        return batch * -(-pps // ppb)
    if base in ("fixed", "jaxlib", "kernel"):
        return batch * num_kv_heads
    return 0


def dispatch_choice_key(
    *, quantized: bool, num_kv_heads: int, num_groups: int, head_dim: int,
    page_size: int, pps: int, pages_per_compute_block: int = 4,
    impl: str = "auto", pages_per_block: int = 0, verify_len: int = 0,
) -> tuple:
    """The per-config key ``paged_attention_op`` records its dispatch
    decision under ``dispatch_choices``. One function so engines can look
    up THEIR OWN entry instead of guessing across a process-global dict
    (several engines can trace in one process — the autotuner's candidate
    sweep). The REQUESTED ``impl`` and ``pages_per_block`` are part of the
    key: two same-geometry engines pinned to different kernels must not
    share (and overwrite) one record. ``verify_len`` > 0 marks the
    speculative draft-block verify dispatch (``paged_verify_op``) — its
    decision ("native_verify" fused sweep vs "unrolled") is a different
    choice than the single-query decode's and must not alias it."""
    blocks = divisor_blocks(pages_per_compute_block, pps)
    return (impl, pages_per_block, quantized, num_kv_heads, num_groups,
            head_dim, page_size, blocks, pps, verify_len)


def divisor_blocks(pages_per_compute_block: int, pps: int) -> int:
    """Largest divisor of ``pps`` that fits ``pages_per_compute_block`` —
    the per-call block count the one-page kernels launch with. Shared so
    consumers (the fused-verify probe) derive it from the geometry instead
    of indexing the dispatch key tuple positionally."""
    return max(
        (d for d in range(1, min(pages_per_compute_block, pps) + 1)
         if pps % d == 0),
        default=1,
    )


def dispatch_key_is_verify(key) -> bool:
    """True when a ``dispatch_choices`` key records a speculative
    draft-block verify dispatch (``paged_verify_op``) rather than a
    single-query decode. The ONLY place outside ``dispatch_choice_key``
    allowed to know the tuple layout — consumers (bench's decode-impl
    summary, trace filters) must call this instead of indexing, so the
    next field appended to the key cannot silently break their filters."""
    return isinstance(key, tuple) and len(key) >= 10 and bool(key[9])
# per-config record of what the auto-dispatch chain actually chose
# ("native" | "native_folded" | "fixed" | "jaxlib" | "reference") —
# bench records surface
# this so a reference-fallback run cannot masquerade as a kernel
# measurement (same honesty contract as attn_fallback / scan_chunk_active)
dispatch_choices: dict = {}
# NOTE on grid-step accounting: the analytic count is batch-dependent, so
# it is never cached here — consumers read WHICH impl ran from
# dispatch_choices (keyed per requested impl + geometry) and compute
# paged_grid_steps() against their own live batch/ppb.
# probe keys whose latest failure was transient (RESOURCE_EXHAUSTED etc.):
# transient failures are never negative-cached, but the dispatch decision is
# made at TRACE time and baked into the compiled program — a transient probe
# error during the first trace silently downgrades that shape until retrace.
# The chain marks affected dispatch_choices with "!transient-probe" so bench
# records can flag the downgrade instead of presenting it as a settled pick.
transient_probe_keys: set = set()


def _native_call(q, k_pages, v_pages, lengths, page_indices,
                 *, quantized: bool, pages_per_compute_block: int = 0,
                 folded: bool = False, blocked: bool = False,
                 pages_per_block: int = 0, interpret: bool = False):
    """Adapter: the probe/dispatch launch signature → our native kernels
    (ops/paged_native.py), which take int8 weights and compact scales as
    separate arrays. ``folded`` selects the kv-heads-in-block variant with
    a (B, pps) grid (half the grid steps, BASELINE.md r5 grid-overhead
    analysis); ``blocked`` the multi-page grid-collapsed variant with a
    (B, ceil(pps / pages_per_block)) grid on top of the folding."""
    from distrl_llm_tpu.ops.paged_native import (
        paged_attention_native,
        paged_attention_native_blocked,
        paged_attention_native_folded,
    )

    kw: dict = {"interpret": interpret}
    if blocked:
        kernel = paged_attention_native_blocked
        kw["pages_per_block"] = pages_per_block or DEFAULT_PAGES_PER_BLOCK
    elif folded:
        kernel = paged_attention_native_folded
    else:
        kernel = paged_attention_native
    if quantized:
        return kernel(
            q, k_pages.weight, v_pages.weight, lengths, page_indices,
            k_scales=k_pages.scales, v_scales=v_pages.scales, **kw,
        )
    return kernel(q, k_pages, v_pages, lengths, page_indices, **kw)


def _native_verify_call(q, k_pages, v_pages, lengths, page_indices,
                        *, quantized: bool, pages_per_block: int = 0,
                        interpret: bool = False):
    """Adapter for the fused draft-block verify kernel
    (ops/paged_native.py::paged_attention_native_verify): q is the whole
    [B, S, H, hd] draft block, pre-scaled; ``lengths`` are the RESIDENT
    counts before the block (the kernel applies the per-query
    ``lengths + i + 1`` causal ladder itself)."""
    from distrl_llm_tpu.ops.paged_native import paged_attention_native_verify

    kw: dict = {
        "interpret": interpret,
        "pages_per_block": pages_per_block or DEFAULT_PAGES_PER_BLOCK,
    }
    if quantized:
        return paged_attention_native_verify(
            q, k_pages.weight, v_pages.weight, lengths, page_indices,
            k_scales=k_pages.scales, v_scales=v_pages.scales, **kw,
        )
    return paged_attention_native_verify(
        q, k_pages, v_pages, lengths, page_indices, **kw,
    )


def _probe_launch(
    fn_name: str,
    quantized: bool,
    num_kv_heads: int,
    num_groups: int,
    head_dim: int,
    page_size: int,
    q_dtype,
    kv_dtype,
    blocks: int,
    pps: int,
    pages_per_block: int = 0,
    verify_len: int = 0,
) -> bool:
    """Per-config probe: compile + run a paged-attention launch at tiny
    shapes on the REAL backend. Launches are validated under the Pallas
    interpreter in CI, but a Mosaic lowering rejection (or jaxlib internal
    kernel drift) would otherwise surface as a compile error inside the
    engine's jitted step — past the point where ``impl="auto"`` could fall
    back. Probing in an isolated computation keeps auto mode graceful.

    Keyed on the quantities that select Mosaic code paths: the launch, the
    quantization flag (scale scratch layout), num_kv_heads (the kernel's
    per-head HBM DMA slice — probing K=1 hid a real Mosaic rejection of
    ``pages.at[head]`` for head_dim 64, first seen on silicon round 3),
    num_groups (3-d vs 4-d block specs via ``num_groups % 8``), head_dim,
    page_size and the compute-block count (VMEM scratch tiling), the
    q/KV dtypes (Mosaic tiles bf16 (16,128) vs f32 (8,128)), and the REAL
    pages_per_sequence — a pps=1 probe compiled a single-page program whose
    DMA pattern differed from the real call's, passing where the real shape
    failed (second silicon lesson of round 3)."""
    key = (fn_name, quantized, num_kv_heads, num_groups, head_dim, page_size,
           q_dtype, kv_dtype, blocks, pps,
           pages_per_block if fn_name in ("native_blocked", "native_verify")
           else 0,
           verify_len if fn_name == "native_verify" else 0)
    if key not in _fixed_launch_state:
        try:
            from distrl_llm_tpu.ops.paged_int8 import (
                paged_attention_gqa,
                paged_attention_int8,
            )

            if fn_name == "native":
                fn = functools.partial(_native_call, quantized=quantized)
            elif fn_name == "native_folded":
                fn = functools.partial(
                    _native_call, quantized=quantized, folded=True)
            elif fn_name == "native_blocked":
                fn = functools.partial(
                    _native_call, quantized=quantized, blocked=True,
                    pages_per_block=pages_per_block)
            elif fn_name == "native_verify":
                fn = None  # verify-shaped probe built below
            elif fn_name == "fixed":
                fn = paged_attention_int8 if quantized else paged_attention_gqa
            else:
                from jax.experimental.pallas.ops.tpu.paged_attention import (
                    paged_attention as fn,
                )

            b = 1  # one sequence at the REAL pages-per-sequence count
            shape = (num_kv_heads, b * pps, page_size, head_dim)
            if quantized:
                kp = vp = init_quantized_pages(shape)
            else:
                kp = vp = jnp.zeros(shape, kv_dtype)
            if fn_name == "native_verify":
                # the fused verify launch takes an S-query block per row and
                # its own Mosaic code path (S·G query rows in the block) —
                # probe it at the REAL draft-block length
                out = _native_verify_call(
                    jnp.zeros(
                        (b, verify_len, num_kv_heads * num_groups, head_dim),
                        q_dtype,
                    ),
                    kp, vp,
                    jnp.ones((b,), jnp.int32),
                    jnp.asarray(
                        make_page_table(b, pps * page_size, page_size)
                    ),
                    quantized=quantized, pages_per_block=pages_per_block,
                )
            else:
                out = fn(
                    jnp.zeros(
                        (b, num_kv_heads * num_groups, head_dim), q_dtype
                    ),
                    kp, vp,
                    jnp.ones((b,), jnp.int32),
                    jnp.asarray(
                        make_page_table(b, pps * page_size, page_size)
                    ),
                    pages_per_compute_block=blocks,
                )
            jax.block_until_ready(out)
            _fixed_launch_state[key] = True
            transient_probe_keys.discard(key)
        except Exception as e:  # noqa: BLE001 — classify before caching
            from distrl_llm_tpu.ops.attention import _TRANSIENT_ERR_MARKS

            transient = any(m in str(e).upper() for m in _TRANSIENT_ERR_MARKS)
            if transient:
                transient_probe_keys.add(key)
            else:
                _fixed_launch_state[key] = False
                transient_probe_keys.discard(key)
            import logging

            logging.getLogger(__name__).warning(
                "paged-attention %s launch unavailable on this backend for "
                "%s (%s)%s",
                fn_name,
                key,
                e,
                " (transient error — not cached, but a trace consuming this"
                " result bakes the downgrade into its compiled program until"
                " retrace; dispatch_choices marks it '!transient-probe')"
                if transient else "",
            )
            return False
    return _fixed_launch_state[key]


def paged_attention_op(
    q: jax.Array,  # [B, H, hd]
    k_pages: jax.Array,
    v_pages: jax.Array,
    lengths: jax.Array,
    page_indices: jax.Array,
    *,
    impl: str = "auto",
    pages_per_compute_block: int = 4,
    pages_per_block: int = 0,
) -> jax.Array:
    """Dispatch: Pallas TPU kernel when available, jnp reference otherwise.

    ``impl``: "auto" (probe-gated kernel chain on TPU backends, reference
    elsewhere), "kernel" (force the corrected jaxlib launch), "native"
    (force our pipeline-gather kernel, ops/paged_native.py),
    "native_folded" / "native_blocked" (its kv-folded and grid-collapsed
    variants — ``pages_per_block`` sizes the blocked kernel's page
    collapse; 0 = DEFAULT_PAGES_PER_BLOCK), or "reference"."""
    use_kernel = impl in (
        "kernel", "native", "native_folded", "native_blocked"
    ) or (impl == "auto" and jax.default_backend() == "tpu")
    choice_key = None
    if use_kernel:
        try:
            from jax.experimental.pallas.ops.tpu.paged_attention import (
                paged_attention,
            )

            # the kernel computes raw q·k (no internal scaling) and requires
            # pages_per_sequence % pages_per_compute_block == 0
            pps = page_indices.shape[1]
            scaled_q = q * (q.shape[-1] ** -0.5)
            quantized = is_quantized_pages(k_pages)
            kw = k_pages.weight if quantized else k_pages
            num_kv_heads = kw.shape[0]
            num_groups = q.shape[1] // num_kv_heads
            head_dim, page_size = kw.shape[-1], kw.shape[-2]
            choice_key = dispatch_choice_key(
                quantized=quantized, num_kv_heads=num_kv_heads,
                num_groups=num_groups, head_dim=head_dim,
                page_size=page_size, pps=pps,
                pages_per_compute_block=pages_per_compute_block,
                impl=impl, pages_per_block=pages_per_block,
            )
            blocks = choice_key[-2]
            # auto mode walks a probe-gated chain (probes run once per
            # config at the REAL kv-head count and pages-per-sequence):
            # - hd % 128 == 0: corrected jaxlib launch (proven, multi-page
            #   DMA blocks) → our native kernel → jaxlib wrapper → jnp
            #   reference;
            # - hd % 128 != 0: our native kernel FIRST — both jaxlib
            #   kernels' manual per-head HBM DMA slice is rejected by
            #   Mosaic for unaligned head_dim (round-3 silicon finding;
            #   ops/paged_native.py), which two rounds of interpreter
            #   parity could not see.
            ppb_eff = max(
                1, min(pages_per_block or DEFAULT_PAGES_PER_BLOCK, pps)
            )
            probe = functools.partial(
                _probe_launch, quantized=quantized,
                num_kv_heads=num_kv_heads, num_groups=num_groups,
                head_dim=head_dim, page_size=page_size,
                q_dtype=scaled_q.dtype, kv_dtype=kw.dtype, blocks=blocks,
                pps=pps, pages_per_block=ppb_eff,
            )
            # native_folded/native_blocked sit BEHIND the silicon-proven
            # native until their kernel-check stanzas PASS on chip (probes
            # run all-zero inputs, so they catch lowering rejections but
            # not a silent miscompile — round-3 lesson); the bench A/B
            # forces them via BENCH_PAGED_IMPL, and the chain order flips
            # in a follow-up once the stanzas land
            chain = (
                ("native", "native_folded", "native_blocked", "fixed",
                 "jaxlib")
                if head_dim % 128
                else ("fixed", "native", "native_folded", "native_blocked",
                      "jaxlib")
            )
            if impl == "kernel":  # forced: corrected launch, no probe
                chain = ("fixed",)
            elif impl == "native":  # forced: our kernel, no probe
                chain = ("native",)
            elif impl == "native_folded":  # forced: kv-folded variant
                chain = ("native_folded",)
            elif impl == "native_blocked":  # forced: grid-collapsed variant
                chain = ("native_blocked",)
            # sticky across calls sharing this choice_key (one trace calls
            # this op once PER LAYER): if any earlier layer's chain was
            # transiently downgraded, the compiled program mixes reference-
            # path layers with kernel layers — a later layer's clean probe
            # must not erase the flag
            transient_seen = dispatch_choices.get(choice_key, "").endswith(
                "!transient-probe"
            )
            dispatch_choices[choice_key] = "reference" + (
                "!transient-probe" if transient_seen else ""
            )
            for fn_name in chain:
                if len(chain) > 1 and not probe(fn_name):
                    pkey = (fn_name, quantized, num_kv_heads, num_groups,
                            head_dim, page_size, scaled_q.dtype, kw.dtype,
                            blocks, pps,
                            ppb_eff if fn_name == "native_blocked" else 0)
                    transient_seen = transient_seen or (
                        pkey in transient_probe_keys
                    )
                    continue
                dispatch_choices[choice_key] = fn_name + (
                    "!transient-probe" if transient_seen else ""
                )
                if fn_name in ("native", "native_folded", "native_blocked"):
                    return _native_call(
                        scaled_q, k_pages, v_pages,
                        lengths.astype(jnp.int32), page_indices,
                        quantized=quantized,
                        folded=fn_name == "native_folded",
                        blocked=fn_name == "native_blocked",
                        pages_per_block=ppb_eff,
                    ).astype(q.dtype)
                if fn_name == "fixed":
                    from distrl_llm_tpu.ops.paged_int8 import (
                        paged_attention_gqa,
                        paged_attention_int8,
                    )

                    fn = (
                        paged_attention_int8
                        if quantized
                        else paged_attention_gqa
                    )
                    return fn(
                        scaled_q, k_pages, v_pages,
                        lengths.astype(jnp.int32), page_indices,
                        pages_per_compute_block=blocks,
                    ).astype(q.dtype)
                return paged_attention(
                    scaled_q, k_pages, v_pages, lengths.astype(jnp.int32),
                    page_indices, pages_per_compute_block=blocks,
                ).astype(q.dtype)
            if transient_seen:
                # every chain member's probe failed and at least one failure
                # was transient: this trace runs the reference path until a
                # retrace re-probes — flag it
                dispatch_choices[choice_key] = "reference!transient-probe"
        except Exception as e:  # noqa: BLE001 — fall back with one warning
            if impl in ("kernel", "native", "native_folded", "native_blocked"):
                raise
            # the chain recorded its pick before launching; the launch
            # failed, so what actually runs below is the reference (keep the
            # transient marker sticky — see above)
            if choice_key is not None:
                dispatch_choices[choice_key] = "reference" + (
                    "!transient-probe" if transient_seen else ""
                )
            global _kernel_fail_warned
            if not _kernel_fail_warned:
                _kernel_fail_warned = True
                import logging

                logging.getLogger(__name__).warning(
                    "paged_attention kernel unavailable (%s); using reference",
                    e,
                )
    if choice_key is None:
        # non-kernel path (CPU/GPU backend or impl="reference") — still a
        # paged dispatch, and the honesty field must say so
        dispatch_choices[("no-kernel-path",)] = "reference"
    return paged_attention_reference(q, k_pages, v_pages, lengths, page_indices)


def paged_verify_reference(
    q: jax.Array,  # [B, S, H, hd] — S-query draft block per row
    k_pages,
    v_pages,
    lengths: jax.Array,  # [B] RESIDENT tokens BEFORE the draft block
    page_indices: jax.Array,
) -> jax.Array:
    """Semantics reference for the draft-block verify: query position i
    attends each row's [0, lengths + i + 1) prefix — the exact per-position
    ladder the unrolled verify path has always dispatched. Returns
    [B, S, H, hd]."""
    return jnp.stack(
        [
            paged_attention_reference(
                q[:, i], k_pages, v_pages, lengths + i + 1, page_indices
            )
            for i in range(q.shape[1])
        ],
        axis=1,
    )


def paged_verify_op(
    q: jax.Array,  # [B, S, H, hd] — S-query draft block per row (UNscaled)
    k_pages,
    v_pages,
    lengths: jax.Array,  # [B] RESIDENT tokens BEFORE the draft block
    page_indices: jax.Array,
    *,
    impl: str = "auto",
    pages_per_compute_block: int = 4,
    pages_per_block: int = 0,
    verify_impl: str = "fused",
) -> jax.Array:
    """Speculative-decode draft-block verify dispatch: the S-query
    attention of one verify forward, in ONE fused blocked sweep when the
    hardware can (``paged_attention_native_verify``), else unrolled into S
    per-position ``paged_attention_op`` dispatches (the pre-fusion
    behavior, exact to the dispatch).

    ``verify_impl``: "fused" (probe-gated fused kernel on TPU for the
    native impl family, unrolled fallback elsewhere) or "unrolled" (force
    per-position dispatch — the A/B control and the interpreter-parity
    anchor). The decision is recorded in ``dispatch_choices`` under the
    verify-marked key (``dispatch_choice_key(..., verify_len=S)``):
    "native_verify" when the fused sweep ran, "unrolled" otherwise — so
    engines/bench can compute the verify step's TRUE grid cost
    (``paged_grid_steps("native_verify", ...)`` × 1 call vs the per-impl
    count × (d+1) calls) instead of guessing."""
    b, s, h, hd = q.shape
    if verify_impl not in ("fused", "unrolled"):
        raise ValueError(
            f"verify_impl must be fused/unrolled, got {verify_impl!r}"
        )
    quantized = is_quantized_pages(k_pages)
    kw = k_pages.weight if quantized else k_pages
    num_kv_heads = kw.shape[0]
    num_groups = h // num_kv_heads
    head_dim, page_size = kw.shape[-1], kw.shape[-2]
    pps = page_indices.shape[1]
    ppb_eff = max(1, min(pages_per_block or DEFAULT_PAGES_PER_BLOCK, pps))
    choice_key = dispatch_choice_key(
        quantized=quantized, num_kv_heads=num_kv_heads,
        num_groups=num_groups, head_dim=head_dim, page_size=page_size,
        pps=pps, pages_per_compute_block=pages_per_compute_block,
        impl=impl, pages_per_block=pages_per_block, verify_len=s,
    )
    # the fused kernel is a native-family launch; "kernel"/"reference"
    # pins have no fused spelling and always unroll onto their own impl
    fused_eligible = (
        verify_impl == "fused"
        and impl in ("auto", "native", "native_folded", "native_blocked")
        and jax.default_backend() == "tpu"
    )
    if fused_eligible:
        scaled_q = q * (hd ** -0.5)
        if _probe_launch(
            "native_verify", quantized, num_kv_heads, num_groups, head_dim,
            page_size, scaled_q.dtype, kw.dtype,
            divisor_blocks(pages_per_compute_block, pps), pps,
            pages_per_block=ppb_eff, verify_len=s,
        ):
            dispatch_choices[choice_key] = "native_verify"
            return _native_verify_call(
                scaled_q, k_pages, v_pages, lengths.astype(jnp.int32),
                page_indices, quantized=quantized, pages_per_block=ppb_eff,
            ).astype(q.dtype)
    # unrolled: S per-position dispatches (each records its own decode
    # dispatch choice; the verify key records that the step ran unrolled)
    dispatch_choices[choice_key] = "unrolled"
    return jnp.stack(
        [
            paged_attention_op(
                q[:, i], k_pages, v_pages, lengths + i + 1, page_indices,
                impl=impl, pages_per_compute_block=pages_per_compute_block,
                pages_per_block=pages_per_block,
            )
            for i in range(s)
        ],
        axis=1,
    )
