"""Native paged decode attention — our own Pallas TPU kernel.

Why this exists (round 3, first silicon): both jaxlib paged-attention
kernels are unusable for head_dim % 128 != 0 models (e.g. Qwen2.5-0.5B,
hd=64, 14q/2kv). Their manual-DMA design slices the KV page array per
kv-head (``pages.at[head_index]`` — MultiPageAsyncCopyDescriptor,
paged_attention_kernel.py:52), and Mosaic rejects any ``tpu.memref_slice``
whose minor dimension is not lane-aligned: "Slice shape along dimension 3
must be aligned to tiling (128), but is 64". The newer ragged kernel
hard-asserts 128-lane accumulator shapes at trace time instead.

This kernel takes the other road: **no manual DMA at all**. The grid is
(batch, kv_head, page) and the page gather happens in the k/v BlockSpec
``index_map``, which reads the scalar-prefetched page table —
``(b, kv, j) -> (kv, table[b, j], 0, 0)``. The pipeline emitter then moves
whole ``[1, page_size, head_dim]`` blocks, never slicing inside the minor
dims — the exact pattern our flash/splash launches already proved on this
Mosaic version at d=64 (tools/tpu_kernel_check.py, S=4096 PASS).

Per (b, kv) series the kernel runs classic online softmax over the pages:
m/l/acc VMEM scratch carried across the innermost grid dimension, page
positions masked against the sequence length, output emitted at the last
page. Compute is skipped (``pl.when``) for pages past the length; their
DMAs still run — the admission/capacity win of paging is unchanged, and
bounding the DMA walk per row is a follow-up (bucketed pps compiles).

The int8 path consumes the engine's COMPACT per-token scales ([K, P, ps,
1] f32, see ops/paged_int8.py) directly: dequantization is one broadcast
multiply in VMEM, so int8 stays a bandwidth win (~1.03 bytes/element
moved) rather than the 5 bytes/element of jaxlib's pre-broadcast wrapper.

Parity: CI pins numerics against ``paged_attention_reference`` under the
Pallas interpreter; tools/tpu_kernel_check.py revalidates the Mosaic
lowering + numerics on silicon (SURVEY §2b N1/N10).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.pallas.ops.tpu.paged_attention.quantization_utils import (
    MAX_INT8,  # 127.5 — the to_int8/from_int8 contract the pages use
)

NEG_INF = -1e30

# jax 0.7 renamed TPUCompilerParams → CompilerParams; support both so the
# interpret-mode parity suite runs on either generation (the old name was
# one of the pre-existing "Pallas interpret" CI failures — it was an API
# drift, not an interpreter limitation)
CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)


def _paged_kernel(
    lengths_ref,  # SMEM [B] i32 (scalar prefetch)
    tables_ref,  # SMEM [B, pps] i32 (scalar prefetch)
    q_ref,  # VMEM [G, hd] — this (b, kv)'s query group
    k_ref,  # VMEM [1, ps, hd] — page j of kv head kv (gathered by index_map)
    v_ref,  # VMEM [1, ps, hd]
    k_s_ref,  # VMEM [1, ps, 1] f32 compact scales, or None (unquantized)
    v_s_ref,
    o_ref,  # VMEM [G, hd]
    m_scr,  # VMEM [G, 1] f32 running max
    l_scr,  # VMEM [G, 1] f32 running denominator
    acc_scr,  # VMEM [G, hd] f32 running numerator
    *,
    page_size: int,
    pps: int,
):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)  # [G, hd] (pre-scaled)
        k = k_ref[0].astype(jnp.float32)  # [ps, hd]
        v = v_ref[0].astype(jnp.float32)
        if k_s_ref is not None:
            # compact per-token absmax scales; dequant = w * scale /
            # MAX_INT8 (quantization_utils.from_int8 contract — 127.5,
            # not 127: /127 would bias every K/V value by +0.39%)
            k = k * (k_s_ref[0] * (1.0 / MAX_INT8))  # [ps, 1] broadcast
            v = v * (v_s_ref[0] * (1.0 / MAX_INT8))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_scr[...]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [G, ps]
        l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(j == pps - 1)
    def _emit():
        # rows with length 0 (empty decode slots) never accumulate: emit 0
        # instead of 0/0 — their logits are discarded by the done mask, but
        # NaNs must not exist to propagate
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "interpret"),
)
def paged_attention_native(
    q: jax.Array,  # [B, H, hd] — pre-scaled by hd**-0.5 (op contract)
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32, or int8 weight
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pps]
    k_scales: jax.Array | None = None,  # f32 [K, P, ps, 1] compact (int8)
    v_scales: jax.Array | None = None,
    *,
    page_size: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    batch, num_q_heads, head_dim = q.shape
    num_kv_heads, total_pages, ps, head_dim_k = k_pages.shape
    if page_size is None:
        page_size = ps
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"H={num_q_heads} not divisible by K={num_kv_heads}"
        )
    groups = num_q_heads // num_kv_heads
    _, pps = page_indices.shape
    quantized = k_scales is not None

    # index_map gathers pages from the table for EVERY j, including slots
    # past a row's allocation — clamp so garbage entries stay addressable
    # (their compute is masked by the length check)
    tables = jnp.clip(page_indices.astype(jnp.int32), 0, total_pages - 1)
    q4 = q.reshape(batch, num_kv_heads, groups, head_dim)

    # index_maps receive the grid indices plus EVERY scalar-prefetch ref
    # (lengths, tables) appended — the page gather reads the table ref
    q_spec = pl.BlockSpec(
        (None, None, groups, head_dim),
        lambda b, kv, j, lens, tabs: (b, kv, 0, 0),
    )
    kv_spec = pl.BlockSpec(
        (None, 1, page_size, head_dim),
        lambda b, kv, j, lens, tabs: (kv, tabs[b, j], 0, 0),
    )
    scale_spec = pl.BlockSpec(
        (None, 1, page_size, 1),
        lambda b, kv, j, lens, tabs: (kv, tabs[b, j], 0, 0),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q4, k_pages, v_pages]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
        body = functools.partial(_paged_kernel, page_size=page_size, pps=pps)
    else:

        def body(lens, tabs, qr, kr, vr, o, m, l, a):  # noqa: E741
            _paged_kernel(
                lens, tabs, qr, kr, vr, None, None, o, m, l, a,
                page_size=page_size, pps=pps,
            )

    out = pl.pallas_call(
        body,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # lengths, tables ride SMEM
            grid=(batch, num_kv_heads, pps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (None, None, groups, head_dim),
                lambda b, kv, j, lens, tabs: (b, kv, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((groups, 1), jnp.float32),
                pltpu.VMEM((groups, 1), jnp.float32),
                pltpu.VMEM((groups, head_dim), jnp.float32),
            ],
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, groups, head_dim), q.dtype
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables, *operands)
    return out.reshape(batch, num_q_heads, head_dim)


def _paged_kernel_folded(
    lengths_ref,  # SMEM [B] i32
    tables_ref,  # SMEM [B, pps] i32
    q_ref,  # VMEM [K, G, hd] — this row's full query head set
    k_ref,  # VMEM [K, 1, ps, hd] — page j for ALL kv heads (one block)
    v_ref,  # VMEM [K, 1, ps, hd]
    k_s_ref,  # VMEM [K, 1, ps, 1] f32 compact scales, or None
    v_s_ref,
    o_ref,  # VMEM [K, G, hd]
    m_scr,  # VMEM [K, G, 1] f32
    l_scr,  # VMEM [K, G, 1] f32
    acc_scr,  # VMEM [K, G, hd] f32
    *,
    page_size: int,
    pps: int,
):
    """kv-heads-folded variant of ``_paged_kernel``: the kv-head axis rides
    INSIDE the block instead of the grid, halving the grid-step count (the
    0.5B paged rows measured Mosaic's ~1 µs/grid-step floor dominating at
    (B × K × pps) granularity — BASELINE.md r5 analysis) and doubling each
    DMA. Compute is the same online softmax, batched over K via
    dot_general batch dims — no in-kernel head slicing, so the hd%128
    Mosaic constraint this file exists for is still never violated."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)  # [K, G, hd] (pre-scaled)
        k = k_ref[:, 0].astype(jnp.float32)  # [K, ps, hd]
        v = v_ref[:, 0].astype(jnp.float32)
        if k_s_ref is not None:
            k = k * (k_s_ref[:, 0] * (1.0 / MAX_INT8))  # [K, ps, 1] bcast
            v = v * (v_s_ref[:, 0] * (1.0 / MAX_INT8))
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [K, G, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2
        )
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_scr[...]  # [K, G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [K, G, ps]
        l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=2, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [K, G, hd]
        m_scr[...] = m_new

    @pl.when(j == pps - 1)
    def _emit():
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "interpret"),
)
def paged_attention_native_folded(
    q: jax.Array,  # [B, H, hd] — pre-scaled by hd**-0.5 (op contract)
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32, or int8 weight
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pps]
    k_scales: jax.Array | None = None,  # f32 [K, P, ps, 1] compact (int8)
    v_scales: jax.Array | None = None,
    *,
    page_size: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Launch for ``_paged_kernel_folded`` — same contract as
    ``paged_attention_native`` with a (B, pps) grid."""
    batch, num_q_heads, head_dim = q.shape
    num_kv_heads, total_pages, ps, head_dim_k = k_pages.shape
    if page_size is None:
        page_size = ps
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"H={num_q_heads} not divisible by K={num_kv_heads}"
        )
    groups = num_q_heads // num_kv_heads
    _, pps = page_indices.shape
    quantized = k_scales is not None

    tables = jnp.clip(page_indices.astype(jnp.int32), 0, total_pages - 1)
    q4 = q.reshape(batch, num_kv_heads, groups, head_dim)

    q_spec = pl.BlockSpec(
        (None, num_kv_heads, groups, head_dim),
        lambda b, j, lens, tabs: (b, 0, 0, 0),
    )
    kv_spec = pl.BlockSpec(
        (num_kv_heads, 1, page_size, head_dim),
        lambda b, j, lens, tabs: (0, tabs[b, j], 0, 0),
    )
    scale_spec = pl.BlockSpec(
        (num_kv_heads, 1, page_size, 1),
        lambda b, j, lens, tabs: (0, tabs[b, j], 0, 0),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q4, k_pages, v_pages]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
        body = functools.partial(
            _paged_kernel_folded, page_size=page_size, pps=pps)
    else:

        def body(lens, tabs, qr, kr, vr, o, m, l, a):  # noqa: E741
            _paged_kernel_folded(
                lens, tabs, qr, kr, vr, None, None, o, m, l, a,
                page_size=page_size, pps=pps,
            )

    out = pl.pallas_call(
        body,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, pps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (None, num_kv_heads, groups, head_dim),
                lambda b, j, lens, tabs: (b, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((num_kv_heads, groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, groups, head_dim), jnp.float32),
            ],
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, groups, head_dim), q.dtype
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables, *operands)
    return out.reshape(batch, num_q_heads, head_dim)


def _make_blocked_kernel(*, page_size: int, ppb: int, nblk: int,
                         quantized: bool):
    """Kernel body for ``paged_attention_native_blocked``: ``ppb`` pages of
    ALL kv heads folded into one grid step (grid (B, ceil(pps/ppb)) — the
    kv-heads folding of ``_paged_kernel_folded`` composed with a page-axis
    collapse). The round-5 silicon numbers put the one-page kernel at
    Mosaic's ~1 µs/grid-step floor with (B × K × pps) steps per layer
    (BASELINE.md): the kernel is LAUNCH-bound, not bandwidth-bound, so the
    lever is fewer grid steps moving the same bytes.

    The per-page gather stays in BlockSpec ``index_map``s — one per
    in-block page, each reading its own scalar-prefetched table slot
    ``tabs[b, jb·ppb + i]`` — because whole-block pipelined moves are the
    one DMA pattern this Mosaic version has proven at head_dim 64 (the
    reason this file exists; manual in-kernel DMA is exactly what it was
    built to avoid). The kernel body carries the online softmax across the
    in-kernel page loop in REGISTERS, touching the m/l/acc scratch once per
    grid step instead of once per page.

    Ragged tails: pages whose positions all sit past ``length`` contribute
    ``exp(NEG_INF − m)`` = 0 exactly (the block guard ensures the first
    in-block page is valid, so ``m`` is finite before any fully-masked page
    folds in — the 0/0 hazard of an all-masked softmax cannot arise), and
    blocks entirely past the length are skipped by ``pl.when``; their DMAs
    still run against edge-padded table slots, same as the one-page
    kernels' past-allocation slots."""

    def kernel(lengths_ref, tables_ref, q_ref, *rest):
        k_refs = rest[0:ppb]
        v_refs = rest[ppb:2 * ppb]
        if quantized:
            ks_refs = rest[2 * ppb:3 * ppb]
            vs_refs = rest[3 * ppb:4 * ppb]
            o_ref, m_scr, l_scr, acc_scr = rest[4 * ppb:]
        else:
            ks_refs = vs_refs = None
            o_ref, m_scr, l_scr, acc_scr = rest[2 * ppb:]
        b = pl.program_id(0)
        jb = pl.program_id(1)

        @pl.when(jb == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        length = lengths_ref[b]

        @pl.when(jb * (ppb * page_size) < length)
        def _block():
            q = q_ref[...].astype(jnp.float32)  # [K, G, hd] (pre-scaled)
            m = m_scr[...]  # [K, G, 1]
            l = l_scr[...]  # noqa: E741
            acc = acc_scr[...]  # [K, G, hd]
            for i in range(ppb):  # static unroll: ppb block loads per step
                k = k_refs[i][:, 0].astype(jnp.float32)  # [K, ps, hd]
                v = v_refs[i][:, 0].astype(jnp.float32)
                if quantized:
                    # compact per-token scales (see _paged_kernel: 127.5,
                    # the from_int8 contract)
                    k = k * (ks_refs[i][:, 0] * (1.0 / MAX_INT8))
                    v = v * (vs_refs[i][:, 0] * (1.0 / MAX_INT8))
                s = jax.lax.dot_general(
                    q, k, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )  # [K, G, ps]
                pos = (jb * ppb + i) * page_size + jax.lax.broadcasted_iota(
                    jnp.int32, (1, 1, page_size), 2
                )
                s = jnp.where(pos < length, s, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(s, axis=2, keepdims=True))
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)  # [K, G, ps]
                l = alpha * l + jnp.sum(p, axis=2, keepdims=True)  # noqa: E741
                acc = acc * alpha + jax.lax.dot_general(
                    p, v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                m = m_new
            m_scr[...] = m
            l_scr[...] = l
            acc_scr[...] = acc

        @pl.when(jb == nblk - 1)
        def _emit():
            o_ref[...] = (
                acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


def _make_verify_kernel(*, page_size: int, ppb: int, nblk: int, s_len: int,
                        groups: int, quantized: bool):
    """Kernel body for ``paged_attention_native_verify``: the blocked kernel
    (``_make_blocked_kernel``) extended to an S-QUERY draft block per row —
    the speculative-decode verify forward in ONE grid sweep.

    Before this kernel, the verify forward unrolled attention per draft
    position (models/transformer.py issued S separate ``paged_attention_op``
    dispatches per step), multiplying the launch-bound grid walk by (d+1)
    and forfeiting the amortization speculation exists to buy (the round-5
    regime: decode cost ≈ grid steps × Mosaic's ~1 µs/grid-step floor, so S
    sweeps cost S× even though they move the same KV bytes). Here the S
    queries ride INSIDE the block — folded into the query-group axis as
    [K, S·G, hd], the same trick the folded kernel plays with kv heads — so
    the whole (d+1)-token verify costs exactly one blocked sweep:
    grid (B, ceil(pps/ppb)).

    Causality is per QUERY: draft position i (query rows i·G..(i+1)·G−1)
    attends key positions < lengths + i + 1 — the prefix plus draft tokens
    ≤ i, exactly the ``lengths + i + 1`` ladder the unrolled path passed
    per dispatch. The limit is a per-row vector built from a static
    row→position iota, so the mask is one vectorized compare, not a loop.

    Numerical-safety note (why the blocked kernel's first-block-valid
    argument still holds): every query row has at least one attendable
    position — query i's own token sits at position lengths + i <
    lengths + i + 1, and block 0 always covers position 0 < lengths + 1 —
    so the running max is finite after block 0 for every row and
    fully-masked later pages fold in as exact zeros."""

    sg = s_len * groups

    def kernel(lengths_ref, tables_ref, q_ref, *rest):
        k_refs = rest[0:ppb]
        v_refs = rest[ppb:2 * ppb]
        if quantized:
            ks_refs = rest[2 * ppb:3 * ppb]
            vs_refs = rest[3 * ppb:4 * ppb]
            o_ref, m_scr, l_scr, acc_scr = rest[4 * ppb:]
        else:
            ks_refs = vs_refs = None
            o_ref, m_scr, l_scr, acc_scr = rest[2 * ppb:]
        b = pl.program_id(0)
        jb = pl.program_id(1)

        @pl.when(jb == 0)
        def _init():
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        length = lengths_ref[b]
        # per-query-row causal limit: query row r = i·G + g judges draft
        # position i = r // G and may read positions < length + i + 1
        qpos = jax.lax.broadcasted_iota(jnp.int32, (1, sg, 1), 1) // groups
        limit = length + qpos + 1  # [1, S·G, 1]

        # the verify block extends the sequence by s_len tokens (their KV
        # is already resident — written before the attention call), so
        # blocks are live up to length + s_len, not length
        @pl.when(jb * (ppb * page_size) < length + s_len)
        def _block():
            q = q_ref[...].astype(jnp.float32)  # [K, S·G, hd] (pre-scaled)
            m = m_scr[...]  # [K, S·G, 1]
            l = l_scr[...]  # noqa: E741
            acc = acc_scr[...]  # [K, S·G, hd]
            for i in range(ppb):  # static unroll: ppb block loads per step
                k = k_refs[i][:, 0].astype(jnp.float32)  # [K, ps, hd]
                v = v_refs[i][:, 0].astype(jnp.float32)
                if quantized:
                    k = k * (ks_refs[i][:, 0] * (1.0 / MAX_INT8))
                    v = v * (vs_refs[i][:, 0] * (1.0 / MAX_INT8))
                s = jax.lax.dot_general(
                    q, k, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )  # [K, S·G, ps]
                pos = (jb * ppb + i) * page_size + jax.lax.broadcasted_iota(
                    jnp.int32, (1, 1, page_size), 2
                )
                s = jnp.where(pos < limit, s, NEG_INF)  # [K, S·G, ps]
                m_new = jnp.maximum(m, jnp.max(s, axis=2, keepdims=True))
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                l = alpha * l + jnp.sum(p, axis=2, keepdims=True)  # noqa: E741
                acc = acc * alpha + jax.lax.dot_general(
                    p, v, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                m = m_new
            m_scr[...] = m
            l_scr[...] = l
            acc_scr[...] = acc

        @pl.when(jb == nblk - 1)
        def _emit():
            o_ref[...] = (
                acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_block", "interpret"),
)
def paged_attention_native_verify(
    q: jax.Array,  # [B, S, H, hd] — pre-scaled by hd**-0.5 (op contract)
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32, or int8 weight
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B] — RESIDENT tokens BEFORE the draft block
    page_indices: jax.Array,  # i32 [B, pps]
    k_scales: jax.Array | None = None,  # f32 [K, P, ps, 1] compact (int8)
    v_scales: jax.Array | None = None,
    *,
    page_size: int | None = None,
    pages_per_block: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """Launch for ``_make_verify_kernel``: the whole S-token draft-block
    verify in one (B, ceil(pps / pages_per_block)) sweep. The S draft
    tokens' KV must already be resident in the pages (the verify forward
    writes them first); query position i attends keys < lengths + i + 1.
    Returns [B, S, H, hd]."""
    batch, s_len, num_q_heads, head_dim = q.shape
    num_kv_heads, total_pages, ps, head_dim_k = k_pages.shape
    if page_size is None:
        page_size = ps
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"H={num_q_heads} not divisible by K={num_kv_heads}"
        )
    if pages_per_block < 1:
        raise ValueError(
            f"pages_per_block must be >= 1, got {pages_per_block}"
        )
    groups = num_q_heads // num_kv_heads
    _, pps = page_indices.shape
    quantized = k_scales is not None
    ppb = min(pages_per_block, pps)
    nblk = -(-pps // ppb)

    tables = jnp.clip(page_indices.astype(jnp.int32), 0, total_pages - 1)
    pad = nblk * ppb - pps
    if pad:
        tables = jnp.concatenate(
            [tables, jnp.broadcast_to(tables[:, -1:], (batch, pad))], axis=1
        )
    # [B, S, H, hd] → [B, K, S·G, hd]: head h = kv·G + g (the reshape
    # convention every kernel in this file uses), query row r = i·G + g
    q4 = (
        q.reshape(batch, s_len, num_kv_heads, groups, head_dim)
        .transpose(0, 2, 1, 3, 4)
        .reshape(batch, num_kv_heads, s_len * groups, head_dim)
    )

    q_spec = pl.BlockSpec(
        (None, num_kv_heads, s_len * groups, head_dim),
        lambda b, j, lens, tabs: (b, 0, 0, 0),
    )

    def kv_spec(i):
        return pl.BlockSpec(
            (num_kv_heads, 1, page_size, head_dim),
            lambda b, j, lens, tabs, i=i: (0, tabs[b, j * ppb + i], 0, 0),
        )

    def scale_spec(i):
        return pl.BlockSpec(
            (num_kv_heads, 1, page_size, 1),
            lambda b, j, lens, tabs, i=i: (0, tabs[b, j * ppb + i], 0, 0),
        )

    in_specs = (
        [q_spec]
        + [kv_spec(i) for i in range(ppb)]
        + [kv_spec(i) for i in range(ppb)]
    )
    operands = [q4] + [k_pages] * ppb + [v_pages] * ppb
    if quantized:
        in_specs += (
            [scale_spec(i) for i in range(ppb)]
            + [scale_spec(i) for i in range(ppb)]
        )
        operands += [k_scales] * ppb + [v_scales] * ppb

    out = pl.pallas_call(
        _make_verify_kernel(
            page_size=page_size, ppb=ppb, nblk=nblk, s_len=s_len,
            groups=groups, quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, nblk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (None, num_kv_heads, s_len * groups, head_dim),
                lambda b, j, lens, tabs: (b, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((num_kv_heads, s_len * groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, s_len * groups, 1), jnp.float32),
                pltpu.VMEM(
                    (num_kv_heads, s_len * groups, head_dim), jnp.float32
                ),
            ],
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, s_len * groups, head_dim), q.dtype
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables, *operands)
    return (
        out.reshape(batch, num_kv_heads, s_len, groups, head_dim)
        .transpose(0, 2, 1, 3, 4)
        .reshape(batch, s_len, num_q_heads, head_dim)
    )


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "pages_per_block", "interpret"),
)
def paged_attention_native_blocked(
    q: jax.Array,  # [B, H, hd] — pre-scaled by hd**-0.5 (op contract)
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32, or int8 weight
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pps]
    k_scales: jax.Array | None = None,  # f32 [K, P, ps, 1] compact (int8)
    v_scales: jax.Array | None = None,
    *,
    page_size: int | None = None,
    pages_per_block: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """Launch for ``_make_blocked_kernel`` — same contract as
    ``paged_attention_native`` with a (B, ceil(pps / pages_per_block))
    grid. ``pages_per_block`` is clamped to [1, pps]; at 1 this is the
    folded kernel bit-for-bit (same op order — pinned by tests)."""
    batch, num_q_heads, head_dim = q.shape
    num_kv_heads, total_pages, ps, head_dim_k = k_pages.shape
    if page_size is None:
        page_size = ps
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"H={num_q_heads} not divisible by K={num_kv_heads}"
        )
    if pages_per_block < 1:
        raise ValueError(
            f"pages_per_block must be >= 1, got {pages_per_block}"
        )
    groups = num_q_heads // num_kv_heads
    _, pps = page_indices.shape
    quantized = k_scales is not None
    ppb = min(pages_per_block, pps)
    nblk = -(-pps // ppb)

    tables = jnp.clip(page_indices.astype(jnp.int32), 0, total_pages - 1)
    pad = nblk * ppb - pps
    if pad:
        # ragged final block: edge-pad the table so every in-block
        # index_map slot is addressable; padded pages are fully
        # length-masked in the kernel
        tables = jnp.concatenate(
            [tables, jnp.broadcast_to(tables[:, -1:], (batch, pad))], axis=1
        )
    q4 = q.reshape(batch, num_kv_heads, groups, head_dim)

    q_spec = pl.BlockSpec(
        (None, num_kv_heads, groups, head_dim),
        lambda b, j, lens, tabs: (b, 0, 0, 0),
    )

    def kv_spec(i):
        return pl.BlockSpec(
            (num_kv_heads, 1, page_size, head_dim),
            lambda b, j, lens, tabs, i=i: (0, tabs[b, j * ppb + i], 0, 0),
        )

    def scale_spec(i):
        return pl.BlockSpec(
            (num_kv_heads, 1, page_size, 1),
            lambda b, j, lens, tabs, i=i: (0, tabs[b, j * ppb + i], 0, 0),
        )

    # the SAME pool array rides as ppb inputs, one per in-block page — each
    # gets its own index_map gather, so the pipeline emitter still only
    # ever moves whole [K, 1, ps, hd] blocks (never slicing the minor dims)
    in_specs = (
        [q_spec]
        + [kv_spec(i) for i in range(ppb)]
        + [kv_spec(i) for i in range(ppb)]
    )
    operands = [q4] + [k_pages] * ppb + [v_pages] * ppb
    if quantized:
        in_specs += (
            [scale_spec(i) for i in range(ppb)]
            + [scale_spec(i) for i in range(ppb)]
        )
        operands += [k_scales] * ppb + [v_scales] * ppb

    out = pl.pallas_call(
        _make_blocked_kernel(
            page_size=page_size, ppb=ppb, nblk=nblk, quantized=quantized
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, nblk),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (None, num_kv_heads, groups, head_dim),
                lambda b, j, lens, tabs: (b, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((num_kv_heads, groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, groups, head_dim), jnp.float32),
            ],
        ),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, groups, head_dim), q.dtype
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables, *operands)
    return out.reshape(batch, num_q_heads, head_dim)
