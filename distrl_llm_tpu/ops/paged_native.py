"""Native paged decode attention — our own Pallas TPU kernel.

Why this exists (round 3, first silicon): both jaxlib paged-attention
kernels are unusable for head_dim % 128 != 0 models (e.g. Qwen2.5-0.5B,
hd=64, 14q/2kv). Their manual-DMA design slices the KV page array per
kv-head (``pages.at[head_index]`` — MultiPageAsyncCopyDescriptor,
paged_attention_kernel.py:52), and Mosaic rejects any ``tpu.memref_slice``
whose minor dimension is not lane-aligned: "Slice shape along dimension 3
must be aligned to tiling (128), but is 64". The newer ragged kernel
hard-asserts 128-lane accumulator shapes at trace time instead.

This kernel takes the other road: **no manual DMA at all**. The grid is
(batch, kv_head, page) and the page gather happens in the k/v BlockSpec
``index_map``, which reads the scalar-prefetched page table —
``(b, kv, j) -> (kv, table[b, j], 0, 0)``. The pipeline emitter then moves
whole ``[1, page_size, head_dim]`` blocks, never slicing inside the minor
dims — the exact pattern our flash/splash launches already proved on this
Mosaic version at d=64 (tools/tpu_kernel_check.py, S=4096 PASS).

Per (b, kv) series the kernel runs classic online softmax over the pages:
m/l/acc VMEM scratch carried across the innermost grid dimension, page
positions masked against the sequence length, output emitted at the last
page. Compute is skipped (``pl.when``) for pages past the length; their
DMAs still run — the admission/capacity win of paging is unchanged, and
bounding the DMA walk per row is a follow-up (bucketed pps compiles).

The int8 path consumes the engine's COMPACT per-token scales ([K, P, ps,
1] f32, see ops/paged_int8.py) directly: dequantization is one broadcast
multiply in VMEM, so int8 stays a bandwidth win (~1.03 bytes/element
moved) rather than the 5 bytes/element of jaxlib's pre-broadcast wrapper.

Parity: CI pins numerics against ``paged_attention_reference`` under the
Pallas interpreter; tools/tpu_kernel_check.py revalidates the Mosaic
lowering + numerics on silicon (SURVEY §2b N1/N10).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.pallas.ops.tpu.paged_attention.quantization_utils import (
    MAX_INT8,  # 127.5 — the to_int8/from_int8 contract the pages use
)

NEG_INF = -1e30


def _paged_kernel(
    lengths_ref,  # SMEM [B] i32 (scalar prefetch)
    tables_ref,  # SMEM [B, pps] i32 (scalar prefetch)
    q_ref,  # VMEM [G, hd] — this (b, kv)'s query group
    k_ref,  # VMEM [1, ps, hd] — page j of kv head kv (gathered by index_map)
    v_ref,  # VMEM [1, ps, hd]
    k_s_ref,  # VMEM [1, ps, 1] f32 compact scales, or None (unquantized)
    v_s_ref,
    o_ref,  # VMEM [G, hd]
    m_scr,  # VMEM [G, 1] f32 running max
    l_scr,  # VMEM [G, 1] f32 running denominator
    acc_scr,  # VMEM [G, hd] f32 running numerator
    *,
    page_size: int,
    pps: int,
):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)  # [G, hd] (pre-scaled)
        k = k_ref[0].astype(jnp.float32)  # [ps, hd]
        v = v_ref[0].astype(jnp.float32)
        if k_s_ref is not None:
            # compact per-token absmax scales; dequant = w * scale /
            # MAX_INT8 (quantization_utils.from_int8 contract — 127.5,
            # not 127: /127 would bias every K/V value by +0.39%)
            k = k * (k_s_ref[0] * (1.0 / MAX_INT8))  # [ps, 1] broadcast
            v = v * (v_s_ref[0] * (1.0 / MAX_INT8))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1
        )
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_scr[...]  # [G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [G, ps]
        l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = m_new

    @pl.when(j == pps - 1)
    def _emit():
        # rows with length 0 (empty decode slots) never accumulate: emit 0
        # instead of 0/0 — their logits are discarded by the done mask, but
        # NaNs must not exist to propagate
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "interpret"),
)
def paged_attention_native(
    q: jax.Array,  # [B, H, hd] — pre-scaled by hd**-0.5 (op contract)
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32, or int8 weight
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pps]
    k_scales: jax.Array | None = None,  # f32 [K, P, ps, 1] compact (int8)
    v_scales: jax.Array | None = None,
    *,
    page_size: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    batch, num_q_heads, head_dim = q.shape
    num_kv_heads, total_pages, ps, head_dim_k = k_pages.shape
    if page_size is None:
        page_size = ps
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"H={num_q_heads} not divisible by K={num_kv_heads}"
        )
    groups = num_q_heads // num_kv_heads
    _, pps = page_indices.shape
    quantized = k_scales is not None

    # index_map gathers pages from the table for EVERY j, including slots
    # past a row's allocation — clamp so garbage entries stay addressable
    # (their compute is masked by the length check)
    tables = jnp.clip(page_indices.astype(jnp.int32), 0, total_pages - 1)
    q4 = q.reshape(batch, num_kv_heads, groups, head_dim)

    # index_maps receive the grid indices plus EVERY scalar-prefetch ref
    # (lengths, tables) appended — the page gather reads the table ref
    q_spec = pl.BlockSpec(
        (None, None, groups, head_dim),
        lambda b, kv, j, lens, tabs: (b, kv, 0, 0),
    )
    kv_spec = pl.BlockSpec(
        (None, 1, page_size, head_dim),
        lambda b, kv, j, lens, tabs: (kv, tabs[b, j], 0, 0),
    )
    scale_spec = pl.BlockSpec(
        (None, 1, page_size, 1),
        lambda b, kv, j, lens, tabs: (kv, tabs[b, j], 0, 0),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q4, k_pages, v_pages]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
        body = functools.partial(_paged_kernel, page_size=page_size, pps=pps)
    else:

        def body(lens, tabs, qr, kr, vr, o, m, l, a):  # noqa: E741
            _paged_kernel(
                lens, tabs, qr, kr, vr, None, None, o, m, l, a,
                page_size=page_size, pps=pps,
            )

    out = pl.pallas_call(
        body,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # lengths, tables ride SMEM
            grid=(batch, num_kv_heads, pps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (None, None, groups, head_dim),
                lambda b, kv, j, lens, tabs: (b, kv, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((groups, 1), jnp.float32),
                pltpu.VMEM((groups, 1), jnp.float32),
                pltpu.VMEM((groups, head_dim), jnp.float32),
            ],
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, groups, head_dim), q.dtype
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables, *operands)
    return out.reshape(batch, num_q_heads, head_dim)


def _paged_kernel_folded(
    lengths_ref,  # SMEM [B] i32
    tables_ref,  # SMEM [B, pps] i32
    q_ref,  # VMEM [K, G, hd] — this row's full query head set
    k_ref,  # VMEM [K, 1, ps, hd] — page j for ALL kv heads (one block)
    v_ref,  # VMEM [K, 1, ps, hd]
    k_s_ref,  # VMEM [K, 1, ps, 1] f32 compact scales, or None
    v_s_ref,
    o_ref,  # VMEM [K, G, hd]
    m_scr,  # VMEM [K, G, 1] f32
    l_scr,  # VMEM [K, G, 1] f32
    acc_scr,  # VMEM [K, G, hd] f32
    *,
    page_size: int,
    pps: int,
):
    """kv-heads-folded variant of ``_paged_kernel``: the kv-head axis rides
    INSIDE the block instead of the grid, halving the grid-step count (the
    0.5B paged rows measured Mosaic's ~1 µs/grid-step floor dominating at
    (B × K × pps) granularity — BASELINE.md r5 analysis) and doubling each
    DMA. Compute is the same online softmax, batched over K via
    dot_general batch dims — no in-kernel head slicing, so the hd%128
    Mosaic constraint this file exists for is still never violated."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]

    @pl.when(j * page_size < length)
    def _page():
        q = q_ref[...].astype(jnp.float32)  # [K, G, hd] (pre-scaled)
        k = k_ref[:, 0].astype(jnp.float32)  # [K, ps, hd]
        v = v_ref[:, 0].astype(jnp.float32)
        if k_s_ref is not None:
            k = k * (k_s_ref[:, 0] * (1.0 / MAX_INT8))  # [K, ps, 1] bcast
            v = v * (v_s_ref[:, 0] * (1.0 / MAX_INT8))
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [K, G, ps]
        pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, page_size), 2
        )
        s = jnp.where(pos < length, s, NEG_INF)
        m_prev = m_scr[...]  # [K, G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [K, G, ps]
        l_scr[...] = alpha * l_scr[...] + jnp.sum(p, axis=2, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # [K, G, hd]
        m_scr[...] = m_new

    @pl.when(j == pps - 1)
    def _emit():
        o_ref[...] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "interpret"),
)
def paged_attention_native_folded(
    q: jax.Array,  # [B, H, hd] — pre-scaled by hd**-0.5 (op contract)
    k_pages: jax.Array,  # [K, P, ps, hd] bf16/f32, or int8 weight
    v_pages: jax.Array,
    lengths: jax.Array,  # i32 [B]
    page_indices: jax.Array,  # i32 [B, pps]
    k_scales: jax.Array | None = None,  # f32 [K, P, ps, 1] compact (int8)
    v_scales: jax.Array | None = None,
    *,
    page_size: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Launch for ``_paged_kernel_folded`` — same contract as
    ``paged_attention_native`` with a (B, pps) grid."""
    batch, num_q_heads, head_dim = q.shape
    num_kv_heads, total_pages, ps, head_dim_k = k_pages.shape
    if page_size is None:
        page_size = ps
    if head_dim_k != head_dim:
        raise ValueError(f"head_dim mismatch: {head_dim_k} vs {head_dim}")
    if num_q_heads % num_kv_heads:
        raise ValueError(
            f"H={num_q_heads} not divisible by K={num_kv_heads}"
        )
    groups = num_q_heads // num_kv_heads
    _, pps = page_indices.shape
    quantized = k_scales is not None

    tables = jnp.clip(page_indices.astype(jnp.int32), 0, total_pages - 1)
    q4 = q.reshape(batch, num_kv_heads, groups, head_dim)

    q_spec = pl.BlockSpec(
        (None, num_kv_heads, groups, head_dim),
        lambda b, j, lens, tabs: (b, 0, 0, 0),
    )
    kv_spec = pl.BlockSpec(
        (num_kv_heads, 1, page_size, head_dim),
        lambda b, j, lens, tabs: (0, tabs[b, j], 0, 0),
    )
    scale_spec = pl.BlockSpec(
        (num_kv_heads, 1, page_size, 1),
        lambda b, j, lens, tabs: (0, tabs[b, j], 0, 0),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    operands = [q4, k_pages, v_pages]
    if quantized:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
        body = functools.partial(
            _paged_kernel_folded, page_size=page_size, pps=pps)
    else:

        def body(lens, tabs, qr, kr, vr, o, m, l, a):  # noqa: E741
            _paged_kernel_folded(
                lens, tabs, qr, kr, vr, None, None, o, m, l, a,
                page_size=page_size, pps=pps,
            )

    out = pl.pallas_call(
        body,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, pps),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (None, num_kv_heads, groups, head_dim),
                lambda b, j, lens, tabs: (b, 0, 0, 0),
            ),
            scratch_shapes=[
                pltpu.VMEM((num_kv_heads, groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, groups, 1), jnp.float32),
                pltpu.VMEM((num_kv_heads, groups, head_dim), jnp.float32),
            ],
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")
        ),
        out_shape=jax.ShapeDtypeStruct(
            (batch, num_kv_heads, groups, head_dim), q.dtype
        ),
        interpret=interpret,
    )(lengths.astype(jnp.int32), tables, *operands)
    return out.reshape(batch, num_q_heads, head_dim)
