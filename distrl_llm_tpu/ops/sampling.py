"""Batched token sampling under jit: temperature, top-p (nucleus), greedy.

Replaces the vLLM sampler the reference drives through SamplingParams
(distributed_actor.py:43–48 — temperature, top_p=0.95, n candidates). All ops
are fixed-shape and branch-free so the whole decode loop stays on device; the
top-p filter is the exact sort-based formulation (keep the minimal prefix of
the sorted distribution whose mass reaches top_p).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distrl_llm_tpu.ops.attention import NEG_INF


def top_p_filter(logits: jax.Array, top_p: jax.Array | float) -> jax.Array:
    """Mask logits outside the nucleus: sort descending, keep tokens until the
    cumulative probability first reaches ``top_p`` (the token that crosses the
    threshold is kept, matching vLLM/HF semantics). [B, V] → [B, V].

    Membership is mapped back by RANK, not by logit threshold, so ties at the
    cutoff don't expand the nucleus beyond top_p (stable argsort breaks ties
    deterministically by vocab index)."""
    order = jnp.argsort(-logits, axis=-1)  # descending, stable
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens whose prefix mass EXCLUDING them has not yet reached top_p
    keep_sorted = (cum - sorted_probs) < top_p
    ranks = jnp.argsort(order, axis=-1)  # rank of each vocab position
    keep = jnp.take_along_axis(keep_sorted, ranks, axis=-1)
    return jnp.where(keep, logits, NEG_INF)


def sample(
    rng: jax.Array,
    logits: jax.Array,  # [B, V]
    temperature: jax.Array | float,
    top_p: jax.Array | float = 1.0,
) -> jax.Array:
    """Sample token ids [B]. temperature == 0 → greedy (vLLM convention).

    Temperature and top_p may be traced scalars so train/eval sampling params
    (1.2/0.95 vs 0.6/0.95 — distributed_trainer.py:53–58) share one compiled
    decode loop.
    """
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits.astype(jnp.float32) / t
    filtered = top_p_filter(scaled, top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1)
    is_greedy = jnp.asarray(temperature, jnp.float32) == 0.0
    return jnp.where(is_greedy, greedy, sampled).astype(jnp.int32)
