"""Batched token sampling under jit: temperature, top-p (nucleus), greedy.

Replaces the vLLM sampler the reference drives through SamplingParams
(distributed_actor.py:43–48 — temperature, top_p=0.95, n candidates). All ops
are fixed-shape and branch-free so the whole decode loop stays on device; the
top-p filter is the exact sort-based formulation (keep the minimal prefix of
the sorted distribution whose mass reaches top_p).
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp

from distrl_llm_tpu.ops.attention import NEG_INF

logger = logging.getLogger(__name__)


def top_p_filter(logits: jax.Array, top_p: jax.Array | float) -> jax.Array:
    """Mask logits outside the nucleus: sort descending, keep tokens until the
    cumulative probability first reaches ``top_p`` (the token that crosses the
    threshold is kept, matching vLLM/HF semantics). [B, V] → [B, V].

    Membership is mapped back by RANK, not by logit threshold, so ties at the
    cutoff don't expand the nucleus beyond top_p (stable argsort breaks ties
    deterministically by vocab index)."""
    order = jnp.argsort(-logits, axis=-1)  # descending, stable
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens whose prefix mass EXCLUDING them has not yet reached top_p
    keep_sorted = (cum - sorted_probs) < top_p
    ranks = jnp.argsort(order, axis=-1)  # rank of each vocab position
    keep = jnp.take_along_axis(keep_sorted, ranks, axis=-1)
    return jnp.where(keep, logits, NEG_INF)


def top_p_filter_bisect(
    logits: jax.Array, top_p: jax.Array | float, iters: int = 16
) -> jax.Array:
    """Sort-free nucleus filter: bisect a probability threshold τ such that
    the kept mass Σ p·[p ≥ τ] just reaches ``top_p``, then keep p ≥ τ.

    Sorting 152k-vocab logits every decode step is the sampler's whole cost on
    TPU; bisection needs only ``iters`` masked reductions, which XLA fuses into
    cheap single-pass kernels. Uses the interval's LOW end so kept mass is
    always ≥ top_p (never drops a token the exact filter would keep); tokens
    tied exactly at the boundary may be kept where the rank-based filter would
    cut them — a measure-zero difference tested against ``top_p_filter``."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p = jnp.asarray(top_p, jnp.float32)

    def body(_, interval):
        lo, hi = interval
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(probs >= mid[..., None], probs, 0.0), axis=-1)
        ok = mass >= top_p  # τ=mid still keeps enough mass → move lo up
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo = jnp.zeros(probs.shape[:-1], jnp.float32)
    hi = jnp.max(probs, axis=-1)
    lo, _ = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return jnp.where(probs >= lo[..., None], logits, NEG_INF)


def top_p_filter_bisect_multiway(
    logits: jax.Array, top_p: jax.Array | float,
    passes: int = 4, k: int = 15,
) -> jax.Array:
    """Nucleus filter with MULTIWAY bisection: each pass tests ``k``
    thresholds of the current interval in one fused read of ``probs`` (the
    k masked reductions share one operand, which XLA's sibling multi-output
    fusion turns into a single V-pass with k accumulators), narrowing the
    interval (k+1)-fold. 4 passes × 15 thresholds reach the same 2^16
    resolution as 16 sequential binary iterations with ~1/4 the HBM
    traffic — at decode shapes ([480, 152k] f32) the binary loop's 16
    un-fusable passes are ~4.6 GB/step of pure sampler reads.

    Same kept-mass guarantee as ``top_p_filter_bisect``: the returned
    threshold always keeps mass ≥ top_p (lo only ever moves onto a tested
    threshold whose kept mass still reached top_p)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p = jnp.asarray(top_p, jnp.float32)
    frac = jnp.arange(1, k + 1, dtype=jnp.float32) / (k + 1)  # (0,1) interior

    def body(_, interval):
        lo, hi = interval  # [...]
        ts = lo[..., None] + (hi - lo)[..., None] * frac  # [..., k], increasing
        # unrolled so XLA sees k sibling reduces over the SAME probs operand
        masses = [
            jnp.sum(
                jnp.where(probs >= ts[..., j][..., None], probs, 0.0), axis=-1
            )
            for j in range(k)
        ]
        mass = jnp.stack(masses, axis=-1)  # [..., k]
        ok = mass >= top_p[..., None]  # top_p may be scalar or per-row
        # robust to float non-monotonicity: take the LARGEST passing
        # threshold and the SMALLEST failing one, not prefix counts
        new_lo = jnp.max(jnp.where(ok, ts, lo[..., None]), axis=-1)
        new_hi = jnp.min(jnp.where(ok, hi[..., None], ts), axis=-1)
        return new_lo, new_hi

    lo = jnp.zeros(probs.shape[:-1], jnp.float32)
    hi = jnp.max(probs, axis=-1)
    lo, _ = jax.lax.fori_loop(0, passes, body, (lo, hi))
    return jnp.where(probs >= lo[..., None], logits, NEG_INF)


TOP_P_IMPLS = {
    "exact": top_p_filter,
    "bisect": top_p_filter_bisect,
    "bisect_mw": top_p_filter_bisect_multiway,
}


def sample(
    rng: jax.Array,
    logits: jax.Array,  # [B, V]
    temperature: jax.Array | float,
    top_p: jax.Array | float = 1.0,
    top_p_impl: str = "bisect",
) -> jax.Array:
    """Sample token ids [B]. temperature == 0 → greedy (vLLM convention).

    Temperature and top_p may be traced scalars so train/eval sampling params
    (1.2/0.95 vs 0.6/0.95 — distributed_trainer.py:53–58) share one compiled
    decode loop.

    ``top_p_impl`` (static): "bisect" (default, sort-free — the fast path),
    "bisect_mw" (multiway bisection, ~1/4 the sampler HBM traffic — flip
    the default once tools/sampler_probe.py confirms the fusion on a real
    chip), or "exact" (rank-based sort filter, byte-identical to the
    reference's vLLM nucleus semantics) for reproducibility runs —
    SamplingConfig.top_p_exact.
    """
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits.astype(jnp.float32) / t
    filtered = TOP_P_IMPLS[top_p_impl](scaled, top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1)
    is_greedy = jnp.asarray(temperature, jnp.float32) == 0.0
    return jnp.where(is_greedy, greedy, sampled).astype(jnp.int32)


# --------------------------------------------------------- fused sampler
# One Pallas program per logits row: temperature scale, bisect top-p
# filter, Gumbel-max categorical draw, and the chosen token's RAW-basis
# logprob — replacing the multi-pass softmax/sort/cumsum pipeline that
# re-reads the [B, V] logits from HBM per pass AND the separate
# token_logprob logsumexp pass (at decode shapes, [480, 152k] f32, the
# sampler pipeline alone is multiple GB/step of HBM traffic; ISSUE 15).
#
# Greedy (temperature == 0) is argmax over the raw row — bit-identical to
# ``sample``'s greedy branch (pinned by tools/quant_smoke.py). The sampled
# path draws via Gumbel-max over the SAME bisect-filtered tempered
# distribution the multi-pass path uses, with uniforms from an in-kernel
# counter-hash PRNG (murmur3 finalizer over (per-row seed, column)) — the
# TPU-native prng primitives don't interpret on CPU, and a pure-jnp hash
# runs identically compiled and interpreted. The draw stream differs from
# jax.random.categorical by construction, so the sampled path is pinned
# DISTRIBUTION-exact (seeded statistical parity, the spec_accept
# precedent), not bit-exact.

#: trace-time dispatch record (ops.paged.dispatch_choices idiom): keyed by
#: (rows, vocab) → "fused" | "xla"; bench reads it for the sample_kernel row
sample_dispatch_choices: dict = {}

SAMPLE_IMPLS = ("auto", "fused", "interpret", "xla")

_sampler_probe_state: dict = {}


def sample_impl_mode() -> str:
    """Resolved DISTRL_SAMPLE_KERNEL mode (validated; default "auto")."""
    mode = os.environ.get("DISTRL_SAMPLE_KERNEL", "auto")
    if mode not in SAMPLE_IMPLS:
        raise ValueError(
            f"DISTRL_SAMPLE_KERNEL must be one of {SAMPLE_IMPLS}, got "
            f"{mode!r}"
        )
    return mode


def _fused_sample_kernel(temp_ref, topp_ref, seed_ref, logits_ref,
                         tok_ref, logp_ref, *, iters: int):
    """One row: (token, raw-basis logprob) in a single pass over the
    logits. Padded columns carry NEG_INF and can never win an argmax or
    contribute mass."""
    raw = logits_ref[...]  # [1, Vp] f32
    t0 = temp_ref[0, 0]
    top_p = topp_ref[0, 0]

    greedy = jnp.argmax(raw, axis=-1)  # [1]

    # tempered softmax (sample()'s exact order: scale, then filter)
    t = jnp.maximum(t0, 1e-6)
    scaled = raw / t
    m = jnp.max(scaled, axis=-1, keepdims=True)
    e = jnp.exp(scaled - m)
    z = jnp.sum(e, axis=-1, keepdims=True)
    probs = e / z

    # bisect the keep threshold (top_p_filter_bisect's math: kept mass is
    # always >= top_p; the LOW end of the interval is the threshold)
    def body(_, interval):
        lo, hi = interval
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(probs >= mid, probs, 0.0), axis=-1,
                       keepdims=True)
        ok = mass >= top_p
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo = jnp.zeros_like(m)
    hi = jnp.max(probs, axis=-1, keepdims=True)
    lo, _ = jax.lax.fori_loop(0, iters, body, (lo, hi))
    filtered = jnp.where(probs >= lo, scaled, NEG_INF)

    # Gumbel-max draw with counter-hash uniforms: murmur3 fmix32 over
    # (seed, column) — identical bits compiled and interpreted
    vp = raw.shape[-1]
    col = jax.lax.broadcasted_iota(jnp.uint32, (1, vp), 1)
    h = col * jnp.uint32(0x9E3779B9) + seed_ref[0, 0].astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    # 23 high bits → u ∈ [2^-24, 1 - 2^-24], every endpoint EXACTLY
    # representable in f32: a 24-bit mapping can round to 1.0f (prob 2^-24
    # per element), where -log(-log(1)) = +inf hands the argmax to an
    # arbitrary — possibly padded — column
    u = (h >> 9).astype(jnp.float32) * jnp.float32(2.0 ** -23) + jnp.float32(
        2.0 ** -24
    )
    gumbel = -jnp.log(-jnp.log(u))
    sampled = jnp.argmax(filtered + gumbel, axis=-1)

    tok = jnp.where(t0 == 0.0, greedy, sampled).astype(jnp.int32)  # [1]

    # raw-basis logprob of the chosen token (token_logprob's math)
    m_raw = jnp.max(raw, axis=-1)
    logz = jnp.log(jnp.sum(jnp.exp(raw - m_raw[..., None]), axis=-1)) + m_raw
    picked = jnp.max(
        jnp.where(
            jax.lax.broadcasted_iota(jnp.int32, (1, vp), 1) == tok[..., None],
            raw, NEG_INF,
        ),
        axis=-1,
    )
    tok_ref[0, 0] = tok[0]
    logp_ref[0, 0] = (picked - logz)[0]


def fused_sample(
    rng: jax.Array,
    logits: jax.Array,  # [B, V]
    temperature: jax.Array | float,
    top_p: jax.Array | float = 1.0,
    *,
    iters: int = 16,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """(tokens [B] i32, raw-basis logprobs [B] f32) in one fused kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, v = logits.shape
    vp = -(-v // 128) * 128
    lg = logits.astype(jnp.float32)
    if vp != v:
        lg = jnp.pad(lg, ((0, 0), (0, vp - v)), constant_values=NEG_INF)
    # one independent 32-bit seed per row off the caller's key — the same
    # key the multi-pass path would hand jax.random.categorical
    seeds = jax.random.bits(rng, (b, 1), jnp.uint32).astype(jnp.int32)
    t = jnp.full((1, 1), 0.0, jnp.float32) + jnp.asarray(
        temperature, jnp.float32
    )
    p = jnp.full((1, 1), 0.0, jnp.float32) + jnp.asarray(top_p, jnp.float32)
    tok, logp = pl.pallas_call(
        functools.partial(_fused_sample_kernel, iters=iters),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, vp), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, 1), jnp.int32),
            jax.ShapeDtypeStruct((b, 1), jnp.float32),
        ],
        interpret=interpret,
    )(t, p, seeds, lg)
    return tok[:, 0], logp[:, 0]


def _sampler_lowers(vocab: int) -> bool:
    """Probe-compile the fused sampler at this vocab — Mosaic rejections
    fire at COMPILE time, past any try/except around a traced call inside
    the engines' jitted steps (the ops/attention._kernel_lowers
    discipline)."""
    key = ("fused_sample", vocab)
    if key not in _sampler_probe_state:
        try:
            jax.block_until_ready(fused_sample(
                jax.random.PRNGKey(0), jnp.zeros((2, vocab), jnp.float32),
                1.0, 0.9,
            ))
            _sampler_probe_state[key] = True
        except Exception as e:  # noqa: BLE001 — fall back, loudly, once
            _sampler_probe_state[key] = False
            logger.warning(
                "fused sampler failed its lowering probe at vocab=%d (%s); "
                "using the multi-pass sampler", vocab, e,
            )
    return _sampler_probe_state[key]


def sample_dispatch(vocab: int, top_p_impl: str) -> tuple[bool, bool]:
    """(use_fused, interpret) per DISTRL_SAMPLE_KERNEL.

    "auto" engages the kernel on TPU when the probe compiles — except under
    an EXPLICIT exact-nucleus pin (top_p_impl="exact" is a reproducibility
    ask the bisect-filter kernel must not silently override). Off-TPU,
    "auto" keeps the multi-pass path (the CPU tier-1 default,
    byte-identical to before the kernel existed)."""
    mode = sample_impl_mode()
    if mode == "xla":
        return False, False
    if mode == "interpret":
        return True, True
    on_tpu = jax.default_backend() == "tpu"
    if mode == "fused":
        return True, not on_tpu
    if top_p_impl == "exact":
        return False, False
    return (on_tpu and _sampler_lowers(vocab)), False


def sample_with_logprob(
    rng: jax.Array,
    logits: jax.Array,  # [B, V]
    temperature: jax.Array | float,
    top_p: jax.Array | float = 1.0,
    *,
    top_p_impl: str = "bisect",
    capture_logprob: bool = False,
    impl: str | None = None,
) -> tuple[jax.Array, jax.Array | None]:
    """The engines' one sampling entry point: (tokens [B], behavior
    logprobs [B] or None). Dispatches to the fused kernel when enabled
    (DISTRL_SAMPLE_KERNEL / probe), else to the multi-pass ``sample`` +
    ``token_logprob`` reference — greedy outputs bit-identical either way."""
    use, interp = (
        sample_dispatch(logits.shape[-1], top_p_impl)
        if impl is None
        else ({"fused": (True, False), "interpret": (True, True),
               "xla": (False, False)}[impl])
    )
    sample_dispatch_choices[tuple(logits.shape)] = (
        "fused" if use else "xla"
    )
    if use:
        tok, logp = fused_sample(rng, logits, temperature, top_p,
                                 interpret=interp)
        return tok, (logp if capture_logprob else None)
    tok = sample(rng, logits, temperature, top_p, top_p_impl=top_p_impl)
    return tok, (token_logprob(logits, tok) if capture_logprob else None)


def token_logprob(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """RAW-model log-probability of ``tokens`` under ``logits`` ([..., V] ×
    [...] → [...] f32). This is the rollout-time BEHAVIOR logprob the
    PPO-clip objective ratios against the learner's recompute. Both sides
    use unscaled log_softmax — the RLHF/vLLM convention. Note this is an
    APPROXIMATION when temperature != 1 or top_p < 1: tokens were actually
    drawn from the tempered/filtered distribution, so the raw-basis ratio
    is not the exact importance ratio against the sampler; it is exact for
    the policy the LOSS optimizes (the raw model), which is why the
    convention is standard."""
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logits.astype(jnp.float32), tokens[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return picked - logz
