"""Batched token sampling under jit: temperature, top-p (nucleus), greedy.

Replaces the vLLM sampler the reference drives through SamplingParams
(distributed_actor.py:43–48 — temperature, top_p=0.95, n candidates). All ops
are fixed-shape and branch-free so the whole decode loop stays on device; the
top-p filter is the exact sort-based formulation (keep the minimal prefix of
the sorted distribution whose mass reaches top_p).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distrl_llm_tpu.ops.attention import NEG_INF


def top_p_filter(logits: jax.Array, top_p: jax.Array | float) -> jax.Array:
    """Mask logits outside the nucleus: sort descending, keep tokens until the
    cumulative probability first reaches ``top_p`` (the token that crosses the
    threshold is kept, matching vLLM/HF semantics). [B, V] → [B, V].

    Membership is mapped back by RANK, not by logit threshold, so ties at the
    cutoff don't expand the nucleus beyond top_p (stable argsort breaks ties
    deterministically by vocab index)."""
    order = jnp.argsort(-logits, axis=-1)  # descending, stable
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # keep tokens whose prefix mass EXCLUDING them has not yet reached top_p
    keep_sorted = (cum - sorted_probs) < top_p
    ranks = jnp.argsort(order, axis=-1)  # rank of each vocab position
    keep = jnp.take_along_axis(keep_sorted, ranks, axis=-1)
    return jnp.where(keep, logits, NEG_INF)


def top_p_filter_bisect(
    logits: jax.Array, top_p: jax.Array | float, iters: int = 16
) -> jax.Array:
    """Sort-free nucleus filter: bisect a probability threshold τ such that
    the kept mass Σ p·[p ≥ τ] just reaches ``top_p``, then keep p ≥ τ.

    Sorting 152k-vocab logits every decode step is the sampler's whole cost on
    TPU; bisection needs only ``iters`` masked reductions, which XLA fuses into
    cheap single-pass kernels. Uses the interval's LOW end so kept mass is
    always ≥ top_p (never drops a token the exact filter would keep); tokens
    tied exactly at the boundary may be kept where the rank-based filter would
    cut them — a measure-zero difference tested against ``top_p_filter``."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p = jnp.asarray(top_p, jnp.float32)

    def body(_, interval):
        lo, hi = interval
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.where(probs >= mid[..., None], probs, 0.0), axis=-1)
        ok = mass >= top_p  # τ=mid still keeps enough mass → move lo up
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

    lo = jnp.zeros(probs.shape[:-1], jnp.float32)
    hi = jnp.max(probs, axis=-1)
    lo, _ = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return jnp.where(probs >= lo[..., None], logits, NEG_INF)


def top_p_filter_bisect_multiway(
    logits: jax.Array, top_p: jax.Array | float,
    passes: int = 4, k: int = 15,
) -> jax.Array:
    """Nucleus filter with MULTIWAY bisection: each pass tests ``k``
    thresholds of the current interval in one fused read of ``probs`` (the
    k masked reductions share one operand, which XLA's sibling multi-output
    fusion turns into a single V-pass with k accumulators), narrowing the
    interval (k+1)-fold. 4 passes × 15 thresholds reach the same 2^16
    resolution as 16 sequential binary iterations with ~1/4 the HBM
    traffic — at decode shapes ([480, 152k] f32) the binary loop's 16
    un-fusable passes are ~4.6 GB/step of pure sampler reads.

    Same kept-mass guarantee as ``top_p_filter_bisect``: the returned
    threshold always keeps mass ≥ top_p (lo only ever moves onto a tested
    threshold whose kept mass still reached top_p)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_p = jnp.asarray(top_p, jnp.float32)
    frac = jnp.arange(1, k + 1, dtype=jnp.float32) / (k + 1)  # (0,1) interior

    def body(_, interval):
        lo, hi = interval  # [...]
        ts = lo[..., None] + (hi - lo)[..., None] * frac  # [..., k], increasing
        # unrolled so XLA sees k sibling reduces over the SAME probs operand
        masses = [
            jnp.sum(
                jnp.where(probs >= ts[..., j][..., None], probs, 0.0), axis=-1
            )
            for j in range(k)
        ]
        mass = jnp.stack(masses, axis=-1)  # [..., k]
        ok = mass >= top_p[..., None]  # top_p may be scalar or per-row
        # robust to float non-monotonicity: take the LARGEST passing
        # threshold and the SMALLEST failing one, not prefix counts
        new_lo = jnp.max(jnp.where(ok, ts, lo[..., None]), axis=-1)
        new_hi = jnp.min(jnp.where(ok, hi[..., None], ts), axis=-1)
        return new_lo, new_hi

    lo = jnp.zeros(probs.shape[:-1], jnp.float32)
    hi = jnp.max(probs, axis=-1)
    lo, _ = jax.lax.fori_loop(0, passes, body, (lo, hi))
    return jnp.where(probs >= lo[..., None], logits, NEG_INF)


TOP_P_IMPLS = {
    "exact": top_p_filter,
    "bisect": top_p_filter_bisect,
    "bisect_mw": top_p_filter_bisect_multiway,
}


def sample(
    rng: jax.Array,
    logits: jax.Array,  # [B, V]
    temperature: jax.Array | float,
    top_p: jax.Array | float = 1.0,
    top_p_impl: str = "bisect",
) -> jax.Array:
    """Sample token ids [B]. temperature == 0 → greedy (vLLM convention).

    Temperature and top_p may be traced scalars so train/eval sampling params
    (1.2/0.95 vs 0.6/0.95 — distributed_trainer.py:53–58) share one compiled
    decode loop.

    ``top_p_impl`` (static): "bisect" (default, sort-free — the fast path),
    "bisect_mw" (multiway bisection, ~1/4 the sampler HBM traffic — flip
    the default once tools/sampler_probe.py confirms the fusion on a real
    chip), or "exact" (rank-based sort filter, byte-identical to the
    reference's vLLM nucleus semantics) for reproducibility runs —
    SamplingConfig.top_p_exact.
    """
    greedy = jnp.argmax(logits, axis=-1)
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits.astype(jnp.float32) / t
    filtered = TOP_P_IMPLS[top_p_impl](scaled, top_p)
    sampled = jax.random.categorical(rng, filtered, axis=-1)
    is_greedy = jnp.asarray(temperature, jnp.float32) == 0.0
    return jnp.where(is_greedy, greedy, sampled).astype(jnp.int32)


def token_logprob(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """RAW-model log-probability of ``tokens`` under ``logits`` ([..., V] ×
    [...] → [...] f32). This is the rollout-time BEHAVIOR logprob the
    PPO-clip objective ratios against the learner's recompute. Both sides
    use unscaled log_softmax — the RLHF/vLLM convention. Note this is an
    APPROXIMATION when temperature != 1 or top_p < 1: tokens were actually
    drawn from the tempered/filtered distribution, so the raw-basis ratio
    is not the exact importance ratio against the sampler; it is exact for
    the policy the LOSS optimizes (the raw model), which is why the
    convention is standard."""
    logz = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logits.astype(jnp.float32), tokens[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return picked - logz
