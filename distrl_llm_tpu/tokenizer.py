"""Tokenizer adapter: fixed-shape encoding and batch decode.

Wraps any HF-style tokenizer (the N7 Rust component in the reference —
SURVEY §2b) behind the two operations the framework needs: fixed-length
encode with explicit pad side (the learner contract, distributed_actor.py:
217–229) and id→text decode for rollouts. A C++ BPE tokenizer with the same
surface plugs in via distrl_llm_tpu.native (built when parity with the
reference's native tokenizer path matters more than the HF dependency).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


def encode_fixed(
    tokenizer,
    texts: Sequence[str],
    max_length: int,
    side: str = "left",
    add_special_tokens: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Encode to exactly [N, max_length] (ids, mask), truncating and padding on
    ``side``. Works with HF fast/slow tokenizers and test doubles exposing
    ``encode(text) -> list[int]``."""
    pad_id = getattr(tokenizer, "pad_token_id", None)
    if pad_id is None:
        pad_id = getattr(tokenizer, "eos_token_id", 0) or 0

    takes_special = _accepts_kwarg(tokenizer.encode, "add_special_tokens")
    ids = np.full((len(texts), max_length), pad_id, dtype=np.int32)
    mask = np.zeros((len(texts), max_length), dtype=np.int32)
    for i, text in enumerate(texts):
        toks = tokenizer.encode(text, add_special_tokens=add_special_tokens) \
            if takes_special else tokenizer.encode(text)
        # HF default truncation_side="right": keep the leading tokens, as the
        # reference's truncation=True encode does regardless of pad side
        toks = toks[:max_length]
        if side == "left":
            ids[i, max_length - len(toks):] = toks
            mask[i, max_length - len(toks):] = 1
        else:
            ids[i, : len(toks)] = toks
            mask[i, : len(toks)] = 1
    return ids, mask


def _accepts_kwarg(method, name: str) -> bool:
    import inspect

    try:
        return name in inspect.signature(method).parameters
    except (TypeError, ValueError):
        return False


def decode_batch(tokenizer, ids: np.ndarray, lengths: np.ndarray) -> list[str]:
    """Decode each row's first ``lengths[i]`` tokens (rollout answers)."""
    takes_skip = _accepts_kwarg(tokenizer.decode, "skip_special_tokens")
    return [
        tokenizer.decode(row[:n].tolist(), skip_special_tokens=True)
        if takes_skip
        else tokenizer.decode(row[:n].tolist())
        for row, n in zip(ids, lengths)
    ]


def load_tokenizer(model_name_or_path: str, prefer_native: bool = True):
    """Load the checkpoint's tokenizer (the reference's
    load_correct_tokenizer, train_distributed.py:46).

    Default path: the C++ N7 parity cores — ``NativeBPETokenizer`` for
    byte-level BPE vocabularies and ``NativeSPMTokenizer`` for sentencepiece
    Unigram ones (Gemma) — both differential-tested against the Rust
    implementation (tests/test_native_tokenizer.py, tests/test_native_spm.py)
    when the checkpoint directory carries a ``tokenizer.json``. Falls back to
    HF AutoTokenizer when the native build is unavailable, the model type is
    neither, or no local tokenizer.json exists (hub model ids)."""
    import logging
    import os

    if prefer_native:
        tj = os.path.join(model_name_or_path, "tokenizer.json")
        if os.path.isfile(tj):
            try:
                import json as _json

                kw = {}
                cfg_path = os.path.join(model_name_or_path, "tokenizer_config.json")
                if os.path.isfile(cfg_path):
                    with open(cfg_path, encoding="utf-8") as f:
                        tok_cfg = _json.load(f)
                    if tok_cfg.get("chat_template"):
                        kw["chat_template"] = tok_cfg["chat_template"]
                with open(tj, encoding="utf-8") as f:
                    tj_dict = _json.load(f)  # parsed once; multi-MB for 7B+
                if (tj_dict.get("model") or {}).get("type") == "Unigram":
                    from distrl_llm_tpu.native.spm import NativeSPMTokenizer

                    return NativeSPMTokenizer.from_hf_dict(tj_dict, **kw)
                from distrl_llm_tpu.native.tokenizer import NativeBPETokenizer

                return NativeBPETokenizer.from_hf_dict(tj_dict, **kw)
            except Exception as e:  # noqa: BLE001 — any native failure → HF path
                logging.getLogger(__name__).warning(
                    "native tokenizer unavailable for %s (%s); using HF",
                    model_name_or_path, e,
                )
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(model_name_or_path)


class CharTokenizer:
    """Byte-level tokenizer with the surface the framework touches (encode/
    decode/pad/eos + chat template via data.py's fallback). Used by the smoke
    path and tests where no HF tokenizer is downloadable (no-egress hosts)."""

    pad_token_id = 0
    eos_token_id = 3
    chat_template = None

    def __init__(self, vocab_size: int = 256):
        self.vocab_size = vocab_size

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        return [min(b, self.vocab_size - 1) for b in text.encode("utf-8")]

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        specials = {self.pad_token_id, self.eos_token_id}
        kept = [i for i in ids if not (skip_special_tokens and i in specials)]
        return bytes(kept).decode("utf-8", errors="ignore")

    def apply_chat_template(
        self, messages, add_generation_prompt=False, tokenize=False, chat_template=None
    ) -> str:
        out = "".join(
            f"<|im_start|>{m['role']}\n{m['content']}<|im_end|>\n" for m in messages
        )
        if add_generation_prompt:
            out += "<|im_start|>assistant\n"
        return out
